"""Serving-stack tests (ISSUE-9): flash-decode kernel parity vs the
jnp twin, KV paging invariants, continuous-batching determinism,
bucket-ladder compile discipline, and the clean-drain contract.

The parity anchor the audit (APX402) pins ``ops/flash_decode.py`` to:
:func:`flash_decode` vs :func:`paged_attention_reference` on randomly
paged caches — unpacked, head-packed d=64, and int8 weight-only
layouts, with inactive rows, straddling pages, and dump-page padding
in every case.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import set_head_packing
from apex_tpu.ops.flash_decode import (flash_decode,
                                       flash_decode_multi,
                                       pack_decode_heads,
                                       paged_attention_multi_reference,
                                       paged_attention_reference,
                                       unpack_decode_heads,
                                       use_decode_head_packing)
from apex_tpu.serving import (DUMP_BLOCK, BucketLadder,
                              CachePoolExhausted, KVCacheConfig,
                              KVCacheManager, Request, ServingEngine,
                              ServingModelConfig, default_cache_config,
                              extract_serving_weights, init_cache,
                              quantize_kv_rows, write_prefill_kv,
                              write_token_kv)
from apex_tpu.testing.standalone_gpt import GPTModel, serve_smoke


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _pack_cache(dense):
    """dense (nb, h, bs, d) -> packed storage (nb, h/2, bs, 2d)."""
    return pack_decode_heads(dense.transpose(0, 2, 1, 3)) \
        .transpose(0, 2, 1, 3)


def make_paged_case(b=3, h=2, d=32, nb=8, bs=8, mp=3, *, seed=0,
                    dtype=jnp.float32, packed=False, int8=False):
    """Random q + paged cache + block tables with the hard cases baked
    in: row 0 inactive (seq_len 0, all-dump table), row 1 straddling a
    page mid-block, row 2 exactly filling its pages."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, d), dtype)
    k_dense = jax.random.normal(ks[1], (nb, h, bs, d), dtype)
    v_dense = jax.random.normal(ks[2], (nb, h, bs, d), dtype)
    rng = np.random.RandomState(seed)
    bt = np.full((b, mp), DUMP_BLOCK, np.int32)
    sl = np.zeros(b, np.int32)
    # rows after 0 get distinct non-dump blocks, lengths cycling over
    # straddle / exact-fill / short
    pool = rng.permutation(np.arange(1, nb))
    lens = [0, mp * bs - bs // 2 - 1, mp * bs] + \
        [1 + rng.randint(mp * bs) for _ in range(b - 3)]
    nxt = 0
    for i in range(1, b):
        sl[i] = lens[i % len(lens)] if i < len(lens) else lens[i]
        pages = -(-int(sl[i]) // bs)
        bt[i, :pages] = pool[nxt:nxt + pages]
        nxt += pages
    ksc = vsc = None
    if int8:
        k_dense, ksc = quantize_kv_rows(k_dense)
        v_dense, vsc = quantize_kv_rows(v_dense)
        ksc = ksc.transpose(0, 1, 2)              # (nb, h, bs)
        vsc = vsc.transpose(0, 1, 2)
    if packed:
        k_cache, v_cache = _pack_cache(k_dense), _pack_cache(v_dense)
    else:
        k_cache, v_cache = k_dense, v_dense
    return (q, k_cache, v_cache, jnp.asarray(bt), jnp.asarray(sl),
            ksc, vsc)


def _assert_close(got, want, dtype):
    tol = 2e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


# ---------------------------------------------------------------------------
# kernel parity (the APX402 anchor)
# ---------------------------------------------------------------------------

class TestFlashDecodeParity:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_parity_unpacked(self, dtype):
        q, kc, vc, bt, sl, _, _ = make_paged_case(dtype=dtype)
        got = flash_decode(q, kc, vc, bt, sl)
        want = paged_attention_reference(q, kc, vc, bt, sl)
        assert got.dtype == dtype
        _assert_close(got, want, dtype)

    def test_parity_packed_d64(self):
        assert use_decode_head_packing(4, 64)
        q, kc, vc, bt, sl, _, _ = make_paged_case(
            b=3, h=4, d=64, nb=8, bs=4, mp=3, packed=True)
        got = flash_decode(q, kc, vc, bt, sl)
        want = paged_attention_reference(q, kc, vc, bt, sl)
        _assert_close(got, want, jnp.float32)

    def test_packed_matches_unpacked_math(self):
        # same dense cache through both layouts -> same attention
        q, kd, vd, bt, sl, _, _ = make_paged_case(b=3, h=4, d=64,
                                                  nb=8, bs=4, mp=3)
        unpacked = flash_decode(q, kd, vd, bt, sl)
        packed = flash_decode(q, _pack_cache(kd), _pack_cache(vd),
                              bt, sl)
        _assert_close(packed, unpacked, jnp.float32)

    def test_parity_int8_unpacked(self):
        q, kc, vc, bt, sl, ksc, vsc = make_paged_case(int8=True)
        got = flash_decode(q, kc, vc, bt, sl, k_scale=ksc,
                           v_scale=vsc)
        want = paged_attention_reference(q, kc, vc, bt, sl,
                                         k_scale=ksc, v_scale=vsc)
        _assert_close(got, want, jnp.float32)

    def test_parity_int8_packed(self):
        q, kd, vd, bt, sl, _, _ = make_paged_case(b=3, h=4, d=64,
                                                  nb=8, bs=4, mp=3)
        kq, ksc = quantize_kv_rows(kd)
        vq, vsc = quantize_kv_rows(vd)
        got = flash_decode(q, _pack_cache(kq), _pack_cache(vq), bt,
                           sl, k_scale=ksc, v_scale=vsc)
        want = paged_attention_reference(
            q, _pack_cache(kq), _pack_cache(vq), bt, sl, k_scale=ksc,
            v_scale=vsc)
        _assert_close(got, want, jnp.float32)

    def test_int8_tracks_f32_attention(self):
        # weight-only int8 is an approximation of the float cache —
        # per-row scales keep it within quantization noise
        q, kd, vd, bt, sl, _, _ = make_paged_case(seed=3)
        exact = flash_decode(q, kd, vd, bt, sl)
        kq, ksc = quantize_kv_rows(kd)
        vq, vsc = quantize_kv_rows(vd)
        quant = flash_decode(q, kq, vq, bt, sl, k_scale=ksc,
                             v_scale=vsc)
        np.testing.assert_allclose(np.asarray(quant), np.asarray(exact),
                                   rtol=0.2, atol=0.1)

    def test_inactive_row_is_exactly_zero(self):
        q, kc, vc, bt, sl, _, _ = make_paged_case()
        assert int(sl[0]) == 0
        out = flash_decode(q, kc, vc, bt, sl)
        assert np.all(np.asarray(out)[0] == 0.0)

    def test_mask_ignores_garbage_past_seq_len(self):
        # poison every position >= seq_len (including whole dump-padded
        # pages) with huge values: masked positions must not leak
        q, kc, vc, bt, sl, _, _ = make_paged_case(seed=5)
        clean = flash_decode(q, kc, vc, bt, sl)
        poisoned = np.asarray(kc).copy()
        poisoned[DUMP_BLOCK] = 1e9
        i = 1                       # the straddling row
        last_page = int(sl[i] - 1) // kc.shape[2]
        blk = int(bt[i, last_page])
        off = int(sl[i]) % kc.shape[2]
        if off:
            poisoned[blk, :, off:, :] = 1e9
        got = flash_decode(q, jnp.asarray(poisoned), vc, bt, sl)
        _assert_close(got, clean, jnp.float32)

    def test_pack_unpack_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (5, 4, 64))
        assert jnp.array_equal(
            unpack_decode_heads(pack_decode_heads(x)), x)

    def test_layout_mismatch_raises(self):
        q, kc, vc, bt, sl, _, _ = make_paged_case()
        with pytest.raises(ValueError, match="head layout"):
            flash_decode(q, kc[:, :, :, :16], vc[:, :, :, :16], bt, sl)
        with pytest.raises(ValueError, match="both k_scale"):
            flash_decode(q, kc, vc, bt, sl,
                         k_scale=jnp.zeros(kc.shape[:2] + kc.shape[2:3]))

    def test_bad_scale_shapes_raise(self):
        # BOTH scales are validated — a misshapen v_scale must raise,
        # not silently dequantize v with garbage factors
        q, kc, vc, bt, sl, _, _ = make_paged_case(int8=True)
        nb, h, bs, _ = kc.shape
        good = jnp.ones((nb, h, bs), jnp.float32)
        with pytest.raises(ValueError, match="k_scale shape"):
            flash_decode(q, kc, vc, bt, sl,
                         k_scale=jnp.ones((nb, h, bs + 1)), v_scale=good)
        with pytest.raises(ValueError, match="v_scale shape"):
            flash_decode(q, kc, vc, bt, sl,
                         k_scale=good, v_scale=jnp.ones((nb, h, bs + 1)))

    def test_packing_escape_hatch(self):
        assert use_decode_head_packing(4, 64)
        set_head_packing(False)
        try:
            assert not use_decode_head_packing(4, 64)
        finally:
            set_head_packing(True)
        assert not use_decode_head_packing(3, 64)   # odd heads
        assert not use_decode_head_packing(4, 32)   # d != 64


def make_multi_case(b=3, t=3, h=2, d=32, nb=10, bs=8, mp=3, *, seed=0,
                    dtype=jnp.float32):
    """Random (b, t) chunk queries + paged cache: row 0 inactive,
    row 1 straddling mid-block, row 2 exactly filling its pages."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), dtype)
    kd = jax.random.normal(ks[1], (nb, h, bs, d), dtype)
    vd = jax.random.normal(ks[2], (nb, h, bs, d), dtype)
    bt = np.full((b, mp), DUMP_BLOCK, np.int32)
    sl = np.zeros(b, np.int32)
    sl[1] = mp * bs - bs // 2 - 1
    bt[1, :2] = [3, 4]
    sl[2] = mp * bs
    bt[2] = [5, 6, 7]
    return q, kd, vd, jnp.asarray(bt), jnp.asarray(sl)


class TestFlashDecodeMultiParity:
    """The APX402 anchor for the multi-token (speculative-verify /
    chunked-prefill) kernel: :func:`flash_decode_multi` vs
    :func:`paged_attention_multi_reference`."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_parity_unpacked(self, dtype):
        q, kd, vd, bt, sl = make_multi_case(dtype=dtype)
        got = flash_decode_multi(q, kd, vd, bt, sl)
        want = paged_attention_multi_reference(q, kd, vd, bt, sl)
        assert got.dtype == dtype
        _assert_close(got, want, dtype)

    def test_parity_packed_d64(self):
        q, kd, vd, bt, sl = make_multi_case(h=4, d=64, bs=4)
        got = flash_decode_multi(q, _pack_cache(kd), _pack_cache(vd),
                                 bt, sl)
        want = paged_attention_multi_reference(
            q, _pack_cache(kd), _pack_cache(vd), bt, sl)
        _assert_close(got, want, jnp.float32)

    def test_parity_int8(self):
        q, kd, vd, bt, sl = make_multi_case(seed=3)
        kq, ksc = quantize_kv_rows(kd)
        vq, vsc = quantize_kv_rows(vd)
        got = flash_decode_multi(q, kq, vq, bt, sl, k_scale=ksc,
                                 v_scale=vsc)
        want = paged_attention_multi_reference(
            q, kq, vq, bt, sl, k_scale=ksc, v_scale=vsc)
        _assert_close(got, want, jnp.float32)

    def test_t1_matches_single_token_decode(self):
        # the degenerate chunk is exactly the decode kernel's math
        q, kc, vc, bt, sl, _, _ = make_paged_case()
        one = flash_decode(q, kc, vc, bt, sl)
        multi = flash_decode_multi(q[:, None], kc, vc, bt, sl)[:, 0]
        _assert_close(multi, one, jnp.float32)

    def test_inactive_and_padding_rows_zero(self):
        # inactive sequences (sl=0) and front-padding rows (negative
        # chunk positions, sl < t) both emit exactly 0
        q, kd, vd, bt, sl = make_multi_case(t=5)
        out = np.asarray(flash_decode_multi(q, kd, vd, bt, sl))
        assert np.all(out[0] == 0.0)            # inactive row
        short = jnp.asarray(np.asarray([2, 2, 2], np.int32))
        out2 = np.asarray(flash_decode_multi(q, kd, vd, bt, short))
        assert np.all(out2[:, :3] == 0.0)       # positions -3..-1
        want = paged_attention_multi_reference(q, kd, vd, bt, short)
        _assert_close(out2, want, jnp.float32)

    def test_per_row_causality(self):
        # poisoning position p must change only rows whose causal
        # span reaches p: row r attends pos <= sl - t + r
        q, kd, vd, bt, sl = make_multi_case(seed=5)
        clean = np.asarray(flash_decode_multi(q, kd, vd, bt, sl))
        i, bs = 2, kd.shape[2]
        last = int(sl[i]) - 1                   # newest position
        blk, off = int(bt[i, last // bs]), last % bs
        poisoned = np.asarray(kd).copy()
        poisoned[blk, :, off, :] += 3.0
        got = np.asarray(flash_decode_multi(
            q, jnp.asarray(poisoned), vd, bt, sl))
        t = q.shape[1]
        # only the final row of row-i's chunk sees the newest slot
        _assert_close(got[i, :t - 1], clean[i, :t - 1], jnp.float32)
        assert not np.allclose(got[i, t - 1], clean[i, t - 1])


# ---------------------------------------------------------------------------
# KV paging invariants
# ---------------------------------------------------------------------------

def _cfg(**kw):
    base = dict(num_layers=1, num_heads=2, head_dim=8, num_blocks=6,
                block_size=4)
    base.update(kw)
    return KVCacheConfig(**base)


class TestKVCacheManager:
    def test_append_past_block_boundary(self):
        mgr = KVCacheManager(_cfg())
        blocks = mgr.alloc("r", 4)          # exactly one full page
        assert len(blocks) == 1 and mgr.seq_len("r") == 4
        blk, off = mgr.append("r")          # token 5 opens page 2
        assert blk != blocks[0] and off == 0
        assert mgr.num_pages("r") == 2 and mgr.seq_len("r") == 5
        blk2, off2 = mgr.append("r")
        assert blk2 == blk and off2 == 1    # stays on the new page

    def test_evict_readmit_reuses_blocks_bitwise(self):
        mgr = KVCacheManager(_cfg())
        first = mgr.alloc("a", 7)           # two pages
        assert mgr.free("a") == first
        again = mgr.alloc("b", 7)
        assert again == first               # LIFO + reversed free
        assert mgr.free_blocks == _cfg().usable_blocks - 2

    def test_pool_exhaustion_and_admission_control(self):
        cfg = _cfg(num_blocks=3)            # 2 usable
        mgr = KVCacheManager(cfg)
        assert mgr.can_admit(4, 4)                      # 2 blocks
        assert not mgr.can_admit(8, 1)                  # needs 3
        # blocks the pool owes in-flight requests count against the
        # free list — the engine's reservation admission delegates here
        assert not mgr.can_admit(4, 4, reserved_blocks=1)
        mgr.alloc("a", 8)                   # both usable blocks
        with pytest.raises(CachePoolExhausted):
            mgr.alloc("b", 1)
        # crossing a block edge with the pool empty is the raced case
        mgr2 = KVCacheManager(cfg)
        mgr2.alloc("a", 4)
        mgr2.alloc("b", 4)
        with pytest.raises(CachePoolExhausted):
            mgr2.append("a")

    def test_block_table_padding_and_overflow(self):
        mgr = KVCacheManager(_cfg())
        mgr.alloc("r", 5)                   # two pages
        bt = mgr.block_table("r", 4)
        assert bt.dtype == np.int32 and list(bt[2:]) == [DUMP_BLOCK] * 2
        assert list(bt[:2]) == mgr.blocks("r")
        with pytest.raises(ValueError, match="max_pages"):
            mgr.block_table("r", 1)

    def test_double_alloc_and_bad_args(self):
        mgr = KVCacheManager(_cfg())
        mgr.alloc("r", 1)
        with pytest.raises(ValueError, match="already"):
            mgr.alloc("r", 1)
        with pytest.raises(ValueError, match="length"):
            mgr.alloc("s", 0)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="dump"):
            _cfg(num_blocks=1)
        with pytest.raises(ValueError, match="kv_dtype"):
            _cfg(kv_dtype="fp8")


class TestCacheWrites:
    @pytest.mark.parametrize("kv_dtype", ["model", "bf16", "int8"])
    def test_token_write_readback(self, kv_dtype):
        cfg = _cfg(kv_dtype=kv_dtype)
        cache = init_cache(cfg)
        k = jax.random.normal(jax.random.PRNGKey(0),
                              (2, cfg.num_heads, cfg.head_dim))
        cache = write_token_kv(cache, cfg, 0, k, k * 2.0,
                               jnp.asarray([1, 3]), jnp.asarray([2, 0]))
        kc, vc, ks, vs = cache.layer(0)
        got_k = paged_attention_reference(
            jnp.ones((2, cfg.num_heads, cfg.head_dim)), kc, vc,
            jnp.asarray([[1], [3]]), jnp.asarray([0, 0]), k_scale=ks,
            v_scale=vs)
        # direct slot readback (dequantized via the twin's helper)
        from apex_tpu.ops.flash_decode import dequantize_kv

        kd = dequantize_kv(kc, ks)
        if cfg.packed:
            kd = unpack_decode_heads(
                kd.transpose(0, 2, 1, 3)).transpose(0, 2, 1, 3)
        tol = {"model": 0, "bf16": 2e-2, "int8": 5e-2}[kv_dtype]
        np.testing.assert_allclose(np.asarray(kd[1, :, 2, :]),
                                   np.asarray(k[0], np.float32),
                                   rtol=tol, atol=tol)
        np.testing.assert_allclose(np.asarray(kd[3, :, 0, :]),
                                   np.asarray(k[1], np.float32),
                                   rtol=tol, atol=tol)
        assert got_k.shape == (2, cfg.num_heads, cfg.head_dim)

    def test_prefill_write_matches_token_writes(self):
        # one whole-prompt scatter == the same tokens written one by one
        cfg = _cfg()
        n, bs = 6, cfg.block_size
        k = jax.random.normal(jax.random.PRNGKey(1),
                              (2 * bs, cfg.num_heads, cfg.head_dim))
        v = jax.random.normal(jax.random.PRNGKey(2), k.shape)
        blocks = jnp.asarray([2, 4])
        whole = write_prefill_kv(init_cache(cfg), cfg, 0, k, v, blocks)
        step = init_cache(cfg)
        for t in range(n):
            step = write_token_kv(
                step, cfg, 0, k[t][None], v[t][None],
                jnp.asarray([int(blocks[t // bs])]),
                jnp.asarray([t % bs]))
        got = np.asarray(whole.k)
        want = np.asarray(step.k)
        # rows past n were zero-padded in the whole-prompt write
        np.testing.assert_array_equal(got[0, 2], want[0, 2])
        np.testing.assert_array_equal(got[0, 4, :, :n - bs],
                                      want[0, 4, :, :n - bs])

    def test_quantize_rows_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 3, 16)) * 5.0
        q, s = quantize_kv_rows(x)
        assert q.dtype == jnp.int8 and s.shape == (4, 3)
        back = np.asarray(q, np.float32) * np.asarray(s)[..., None]
        np.testing.assert_allclose(back, np.asarray(x), atol=np.max(
            np.abs(np.asarray(x))) / 127.0 * 1.01)

    def test_quantize_weight_zero_channel_roundtrip(self):
        # the weight-quantizer analogue of the KV scale floor
        # (ISSUE-16): an all-zero output channel must round-trip to
        # exactly 0.0 with a finite floored scale, never 0/0 = NaN
        from apex_tpu.ops.quant_matmul import (dequantize_weight,
                                               quantize_weight)
        w = jnp.zeros((16, 4), jnp.float32).at[:, 1].set(2.0)
        wq, sc = quantize_weight(w)
        assert np.all(np.isfinite(np.asarray(sc))) and np.all(
            np.asarray(sc) > 0.0)
        deq = np.asarray(dequantize_weight(wq, sc))
        assert np.all(deq[:, 0] == 0.0)
        assert np.all(deq[:, 2:] == 0.0)
        np.testing.assert_allclose(deq[:, 1], 2.0)


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------

class TestBucketLadder:
    def test_pick_rounds_up(self):
        lad = BucketLadder(batch=(1, 2, 4), pages=(2, 8))
        assert lad.pick_batch(1) == 1
        assert lad.pick_batch(3) == 4
        assert lad.pick_pages(3) == 8
        with pytest.raises(ValueError, match="exceeds the ladder"):
            lad.pick_batch(5)

    def test_from_flags_and_validation(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_SERVE_BATCH_BUCKETS", "4,1,2")
        monkeypatch.setenv("APEX_TPU_SERVE_PAGE_BUCKETS", "8")
        lad = BucketLadder.from_flags()
        assert lad.batch == (1, 2, 4) and lad.pages == (8,)
        monkeypatch.setenv("APEX_TPU_SERVE_BATCH_BUCKETS", "0,2")
        with pytest.raises(ValueError, match="positive"):
            BucketLadder.from_flags()


# ---------------------------------------------------------------------------
# serving model + engine
# ---------------------------------------------------------------------------

def _tiny_model(vocab=32, hidden=16, heads=2, layers=2, max_seq=32,
                seed=0):
    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_sequence_length=max_seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    params = jax.jit(model.init)(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, *, ladder, num_blocks=16, block_size=4,
            kv_dtype="model", decode_attention="reference",
            autoresume=None, clock=None):
    cfg = ServingModelConfig.from_model(
        model, prefill_flash=False, decode_attention=decode_attention)
    weights = extract_serving_weights(params, cfg.num_layers)
    cache_cfg = default_cache_config(cfg, num_blocks=num_blocks,
                                     block_size=block_size,
                                     kv_dtype=kv_dtype)
    extra = {} if clock is None else {"clock": clock}
    return ServingEngine(weights, cfg, cache_cfg, ladder=ladder,
                         autoresume=autoresume, **extra)


def _greedy_reference(model, params, prompt, new_tokens):
    """Whole-sequence teacher-forced argmax loop — the no-cache oracle
    the serving stack must reproduce token for token."""
    toks = list(prompt)
    for _ in range(new_tokens):
        logits = model.apply({"params": params},
                             jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, len(toks) - 1])))
    return toks[len(prompt):]


class TestServingModelParity:
    def test_prefill_decode_matches_whole_sequence_model(self):
        # end-to-end: paged prefill + per-token decode == teacher-forced
        # GPTModel.apply greedy generation, bitwise on token ids
        model, params = _tiny_model()
        lad = BucketLadder(batch=(2,), pages=(3,))
        eng = _engine(model, params, ladder=lad)
        prompts = [[3, 7, 1], [11, 2, 9, 4, 5]]
        new = 4
        for i, p in enumerate(prompts):
            eng.submit(Request(rid=f"r{i}", prompt=p,
                               max_new_tokens=new))
        eng.run()
        assert len(eng.done) == 2
        by_rid = {q.rid: q.out_tokens for q in eng.done}
        for i, p in enumerate(prompts):
            want = _greedy_reference(model, params, p, new)
            assert by_rid[f"r{i}"] == want, (i, by_rid[f"r{i}"], want)

    def test_decode_kernel_path_matches_reference_path(self):
        # the same trace through the Pallas kernel and the dense twin
        model, params = _tiny_model(hidden=128, heads=2)  # d=64 packed
        lad = BucketLadder(batch=(2,), pages=(2,))
        prompts = [[5, 1], [9, 3, 2]]
        streams = {}
        for mode in ("kernel", "reference"):
            eng = _engine(model, params, ladder=lad,
                          decode_attention=mode)
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=f"r{i}", prompt=p,
                                   max_new_tokens=3))
            eng.run()
            streams[mode] = {q.rid: q.out_tokens for q in eng.done}
        assert streams["kernel"] == streams["reference"]

    def test_bad_requests_rejected(self):
        model, params = _tiny_model()
        eng = _engine(model, params,
                      ladder=BucketLadder(batch=(2,), pages=(2,)))
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(Request(rid="e", prompt=[], max_new_tokens=1))
        with pytest.raises(ValueError, match="span"):
            eng.submit(Request(rid="big", prompt=[1] * 8,
                               max_new_tokens=4))   # 12 > 2*4
        # non-positive budgets undercount the reservation admission
        # math (prompt + max_new) — rejected at the door
        for bad in (0, -9):
            with pytest.raises(ValueError, match="max_new_tokens"):
                eng.submit(Request(rid="z", prompt=[1, 2, 3],
                                   max_new_tokens=bad))


# Committed divergence bound for the Q8 tier (ISSUE-16): int8
# weight-only quantization may flip at most this fraction of greedy
# tokens vs the float engine on the smoke GPT (measured 0/24 across
# seeds; the bound leaves quantization-noise headroom, it is not a
# target).
Q8_GREEDY_DIVERGENCE_BOUND = 0.10


class TestQ8Serving:
    def test_q8_greedy_tracks_float_within_committed_bound(self):
        from apex_tpu.ops.quant_matmul import quantize_weights
        model, params = _tiny_model(vocab=64, hidden=64, heads=2)
        cfg = ServingModelConfig.from_model(
            model, prefill_flash=False, decode_attention="reference")
        weights = extract_serving_weights(params, cfg.num_layers)
        cache_cfg = default_cache_config(cfg, num_blocks=16,
                                         block_size=4)
        lad = BucketLadder(batch=(2,), pages=(3,))
        prompts = [[3, 7, 1], [11, 2, 9, 4, 5], [1, 2], [6, 6, 6, 6]]
        new = 6
        outs = {}
        for tag, w in (("float", weights),
                       ("q8", quantize_weights(weights))):
            eng = ServingEngine(w, cfg, cache_cfg, ladder=lad)
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=f"r{i}", prompt=p,
                                   max_new_tokens=new))
            eng.run()
            assert len(eng.done) == len(prompts)
            outs[tag] = {q.rid: q.out_tokens for q in eng.done}
        total = sum(len(v) for v in outs["float"].values())
        diverged = sum(a != b for rid in outs["float"]
                       for a, b in zip(outs["float"][rid],
                                       outs["q8"][rid]))
        assert diverged / total <= Q8_GREEDY_DIVERGENCE_BOUND, (
            diverged, total)

    def test_q8_swap_back_and_forth(self):
        # bf16<->int8 requantization swaps both directions; the
        # second direction restores the original treedef bitwise path
        from apex_tpu.ops.quant_matmul import (is_quantized_weights,
                                               quantize_weights)
        model, params = _tiny_model()
        lad = BucketLadder(batch=(2,), pages=(3,))
        eng = _engine(model, params, ladder=lad)
        weights = eng.weights
        eng.swap_weights(quantize_weights(weights))
        assert is_quantized_weights(eng.weights)
        eng.swap_weights(weights)
        assert not is_quantized_weights(eng.weights)
        eng.submit(Request(rid="r", prompt=[3, 1, 4],
                           max_new_tokens=3))
        eng.run()
        assert len(eng.done) == 1


class TestContinuousBatching:
    def _serve(self, model, params, prompts, *, staggered,
               new_tokens=4, **kw):
        eng = _engine(model, params, **kw)
        reqs = [Request(rid=f"r{i}", prompt=list(p),
                        max_new_tokens=new_tokens)
                for i, p in enumerate(prompts)]
        if staggered:
            eng.submit(reqs[0])
            pending = reqs[1:]

            def drip(step):
                if pending:
                    eng.submit(pending.pop(0))

            summary = eng.run(before_tick=drip)
            while pending:
                eng.submit(pending.pop(0))
                summary = eng.run()
        else:
            for r in reqs:
                eng.submit(r)
            summary = eng.run()
        return eng, summary

    def test_determinism_across_admission_interleave(self):
        # same request trace => same tokens, whether everything is
        # admitted up front or admissions drip between decode steps
        model, params = _tiny_model()
        prompts = [[2, 5], [7, 1, 3, 8], [4]]
        kw = dict(ladder=BucketLadder(batch=(1, 2, 4), pages=(2,)),
                  num_blocks=16)
        eng_a, _ = self._serve(model, params, prompts, staggered=False,
                               **kw)
        eng_b, _ = self._serve(model, params, prompts, staggered=True,
                               **kw)
        tok_a = {q.rid: q.out_tokens for q in eng_a.done}
        tok_b = {q.rid: q.out_tokens for q in eng_b.done}
        assert tok_a == tok_b

    def test_determinism_across_bucket_shapes(self):
        # a fatter batch bucket pads with inactive rows; the ladder
        # choice must not change any request's tokens
        model, params = _tiny_model()
        prompts = [[2, 5], [7, 1, 3]]
        tok = {}
        for name, lad in (("tight", BucketLadder(batch=(2,),
                                                 pages=(2,))),
                          ("padded", BucketLadder(batch=(8,),
                                                  pages=(2, 4)))):
            eng, _ = self._serve(model, params, prompts,
                                 staggered=False, ladder=lad,
                                 num_blocks=40)
            tok[name] = {q.rid: q.out_tokens for q in eng.done}
        assert tok["tight"] == tok["padded"]

    def test_resumed_run_reports_lifetime_wall(self):
        # a paused-and-resumed serve (max_steps, or bench's staggered
        # tail admissions) must report lifetime tokens over lifetime
        # in-run wall — not lifetime tokens over only the resumed
        # tail's wall, which inflates tokens/s
        model, params = _tiny_model()
        prompts = [[2, 5], [7, 1, 3]]
        lad = BucketLadder(batch=(2,), pages=(2,))

        def fake_clock():
            fake_clock.t += 1.0
            return fake_clock.t

        summaries = {}
        for name, pause in (("straight", None), ("paused", 2)):
            fake_clock.t = 0.0
            eng = _engine(model, params, ladder=lad, clock=fake_clock)
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=f"r{i}", prompt=list(p),
                                   max_new_tokens=4))
            s = eng.run(max_steps=pause)
            while eng.queue or eng.active:
                s = eng.run()
            summaries[name] = s
        a, b = summaries["straight"], summaries["paused"]
        assert b.tokens_generated == a.tokens_generated
        # the paused serve spends strictly MORE clock inside run()
        # (one extra start/stop pair), never less — so its reported
        # rate can only come out at or below the uninterrupted one
        assert b.wall_s >= a.wall_s
        assert b.tokens_per_sec <= a.tokens_per_sec
        assert b.tokens_per_sec == pytest.approx(
            b.tokens_generated / b.wall_s, abs=0.01)

    def test_decode_rate_excludes_prefill_wall(self):
        # decode_tokens_per_sec divides decode-tick tokens by
        # decode-tick wall only — prefill time (identical across
        # kernel/naive engines) must not dilute the bench ratio
        model, params = _tiny_model()
        lad = BucketLadder(batch=(2,), pages=(2,))

        def fake_clock():
            fake_clock.t += 1.0
            return fake_clock.t
        fake_clock.t = 0.0

        eng = _engine(model, params, ladder=lad, clock=fake_clock)
        for i, p in enumerate([[2, 5], [7, 1, 3]]):
            eng.submit(Request(rid=f"r{i}", prompt=p,
                               max_new_tokens=3))
        s = eng.run()
        # fake clock: every timed region is exactly 1s — decode wall
        # is the tick count, strictly less than the run() wall that
        # also covers the two prefills
        assert s.decode_wall_s == eng.steps == s.decode_steps
        assert s.decode_wall_s < s.wall_s
        assert s.decode_tokens_per_sec == pytest.approx(
            eng.decode_tokens / s.decode_wall_s, abs=0.01)
        # 2 requests x 3 tokens, one each from prefill
        assert eng.decode_tokens == s.tokens_generated - 2

    def test_summary_survives_draining_done(self):
        # lifetime totals come from counters, not from re-summing
        # ``done`` — a long-running caller may pop finished requests
        # to keep host memory flat without corrupting the summary
        model, params = _tiny_model()
        lad = BucketLadder(batch=(2,), pages=(2,))
        eng = _engine(model, params, ladder=lad)
        for i, p in enumerate([[2, 5], [7, 1, 3]]):
            eng.submit(Request(rid=f"r{i}", prompt=p,
                               max_new_tokens=3))
        first = eng.run()
        eng.done.clear()                      # caller consumed results
        eng.submit(Request(rid="late", prompt=[4, 4],
                           max_new_tokens=3))
        second = eng.run()
        assert second.requests_done == 3
        assert second.tokens_generated == first.tokens_generated + 3

    def test_eviction_frees_blocks_for_queued_requests(self):
        # pool too small for all three at once: the third request can
        # only be admitted after an earlier one finishes and frees its
        # blocks — the continuous part of continuous batching
        model, params = _tiny_model()
        lad = BucketLadder(batch=(2,), pages=(2,))
        cfg_blocks = 5                       # 4 usable = two requests
        eng = _engine(model, params, ladder=lad,
                      num_blocks=cfg_blocks)
        for i in range(3):
            eng.submit(Request(rid=f"r{i}", prompt=[1 + i, 2],
                               max_new_tokens=4))
        admitted_at = {}

        def watch(step):
            for rid in eng.active:
                admitted_at.setdefault(rid, step)

        summary = eng.run(before_tick=watch)
        assert summary.requests_done == 3
        assert admitted_at["r2"] > 0         # waited for an eviction
        assert eng.manager.free_blocks == cfg_blocks - 1
        assert summary.tokens_per_sec > 0
        assert summary.latency_p50_ms is not None
        assert summary.latency_p99_ms >= summary.latency_p50_ms

    def test_reservation_counts_future_growth(self):
        # admission must reserve the whole worst case NET of what the
        # pool already owes active requests: r0 holds one page but may
        # grow to 4; admitting r1 (worst 3 pages) against the 3 blocks
        # literally free would exhaust the pool mid-decode
        model, params = _tiny_model(max_seq=32)
        eng = _engine(model, params,
                      ladder=BucketLadder(batch=(2,), pages=(4,)),
                      num_blocks=5)           # 4 usable
        eng.submit(Request(rid="r0", prompt=[1],
                           max_new_tokens=15))   # worst 16 = 4 pages
        eng.submit(Request(rid="r1", prompt=[1, 2],
                           max_new_tokens=10))   # worst 12 = 3 pages
        overlap = []

        def watch(step):
            overlap.append(set(eng.active))

        summary = eng.run(before_tick=watch)     # must not raise
        assert summary.requests_done == 2
        assert not any({"r0", "r1"} <= s for s in overlap)
        assert eng.manager.free_blocks == 4

    def test_clean_drain_on_termination(self):
        class FakeResume:
            source = "sigterm"

            def __init__(self):
                self.calls = 0

            def termination_requested(self):
                self.calls += 1
                return self.calls > 2

        model, params = _tiny_model()
        eng = _engine(model, params,
                      ladder=BucketLadder(batch=(2,), pages=(2,)),
                      autoresume=FakeResume())
        for i in range(2):
            eng.submit(Request(rid=f"r{i}", prompt=[1, 2 + i],
                               max_new_tokens=5))
        summary = eng.run()
        assert summary.drained
        assert summary.requests_preempted == 2
        assert not eng.active and not eng.queue
        # every block returned to the pool — nothing leaks on drain
        assert eng.manager.free_blocks == \
            eng.cache_cfg.usable_blocks

    def test_drain_accounts_for_queued_requests(self):
        # requests accepted but never admitted (batch ladder keeps
        # them queued) must not vanish on SIGTERM: the drain marks
        # them preempted and lands them in done like everything else
        class FakeResume:
            source = "sigterm"

            def __init__(self):
                self.calls = 0

            def termination_requested(self):
                self.calls += 1
                return self.calls > 2

        model, params = _tiny_model()
        eng = _engine(model, params,
                      ladder=BucketLadder(batch=(2,), pages=(2,)),
                      autoresume=FakeResume())
        for i in range(5):                   # only 2 admit at once
            eng.submit(Request(rid=f"r{i}", prompt=[1, 2 + i],
                               max_new_tokens=6))
        summary = eng.run()
        assert summary.drained
        assert summary.requests_preempted == 5
        assert {q.rid for q in eng.done} == {f"r{i}" for i in range(5)}
        assert not eng.active and not eng.queue
        assert eng.manager.free_blocks == eng.cache_cfg.usable_blocks


# ---------------------------------------------------------------------------
# bucket-ladder compile discipline + the serve smoke
# ---------------------------------------------------------------------------

class TestCompileDiscipline:
    def test_warmup_compiles_exactly_the_ladder(self):
        model, params = _tiny_model()
        lad = BucketLadder(batch=(1, 2), pages=(1, 2))
        eng = _engine(model, params, ladder=lad)
        compiles = eng.warmup()
        # one prefill per page rung + the full decode ladder product
        assert len(compiles) == len(lad.pages) + \
            len(lad.batch) * len(lad.pages)
        assert all(v == 1 for v in compiles.values())
        before = dict(eng._compiles)
        eng.warmup()                         # idempotent
        assert eng._compiles == before

    def test_serve_smoke_sanitized_one_compile_per_bucket(self):
        # the acceptance criterion: steady-state serving under
        # sanitize() compiles exactly once per bucket (the smoke holds
        # a post-warmup recompile budget of ZERO; a shape leaking past
        # the ladder would raise RecompileBudgetExceeded here)
        lad = BucketLadder(batch=(2, 4), pages=(2,))
        summary, eng = serve_smoke(
            4, max_new_tokens=3, ladder=lad, num_blocks=24,
            block_size=4, sanitize=True, autoresume=None,
            return_engine=True)
        assert summary.requests_done == 4
        assert summary.tokens_per_sec > 0
        assert len(summary.compiles) == \
            len(lad.pages) + len(lad.batch) * len(lad.pages)
        assert all(v == 1 for v in summary.compiles.values())

    def test_serve_smoke_sigterm_clean_drain(self, tmp_path):
        # the real-signal leg: a SIGTERM mid-serve (flag-only handler)
        # stops admissions, frees the pool, marks in-flight requests
        # preempted, and still lands a full summary + JSONL record
        jsonl = tmp_path / "serve.jsonl"
        summary, eng = serve_smoke(
            4, max_new_tokens=6, jsonl=str(jsonl),
            ladder=BucketLadder(batch=(2, 4), pages=(2,)),
            num_blocks=24, block_size=4, fault="sigterm@2",
            return_engine=True)
        assert summary.drained
        assert summary.requests_preempted > 0
        assert eng.manager.free_blocks == eng.cache_cfg.usable_blocks
        text = jsonl.read_text()
        assert "serve_preempt" in text and "serve_done" in text

    def test_serve_smoke_int8_kv(self):
        summary = serve_smoke(2, max_new_tokens=3, kv_dtype="int8",
                              ladder=BucketLadder(batch=(2,),
                                                  pages=(2,)),
                              num_blocks=16, block_size=4,
                              autoresume=None)
        assert summary.requests_done == 2
        assert summary.tokens_generated == 2 * 3
