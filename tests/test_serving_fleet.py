"""Fleet-serving tests (ISSUE-14): tensor-parallel decode parity, the
KV export/import wire format, the disaggregated prefill→decode
handoff, router scoring + sticky warm routing, the rolling weight
swap, per-replica event stamping, and the fleet-wide trace check.

The TP anchor: a tp=2 :class:`~apex_tpu.serving.ServingEngine` (the
shard_map-wrapped decode/prefill/extend programs under
``serving_tp_plan``) must emit greedy output **token-identical** to
the single-chip engine on the same request trace — the ISSUE-14
acceptance bar, pinned here on the smoke GPT.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor.events import MemorySink
from apex_tpu.serving import (BucketLadder, FleetRouter, KVCacheManager,
                              Replica, Request, RequestJournal,
                              ServingEngine, ServingModelConfig,
                              TPContext, default_cache_config,
                              extract_serving_weights,
                              gather_cache_blocks, prefix_chain_keys,
                              scatter_cache_blocks, serving_tp_plan,
                              transfer_prefix)
from apex_tpu.serving.kv_cache import KVCacheConfig, init_cache
from apex_tpu.testing.standalone_gpt import GPTModel


# ---------------------------------------------------------------------------
# shared fixtures: one smoke GPT + extracted weights per module
# ---------------------------------------------------------------------------

VOCAB, HIDDEN, HEADS, LAYERS, MAX_SEQ = 64, 32, 4, 2, 64


@pytest.fixture(scope="module")
def smoke_weights():
    model = GPTModel(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_layers=LAYERS,
        num_attention_heads=HEADS, max_sequence_length=MAX_SEQ,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    params = jax.jit(model.init)(
        key, jnp.zeros((1, 8), jnp.int32))["params"]
    params2 = jax.jit(model.init)(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = ServingModelConfig.from_model(model)
    return (cfg, extract_serving_weights(params, LAYERS),
            extract_serving_weights(params2, LAYERS))


def make_engine(cfg, weights, *, prefix_share=False, tp=None,
                device=None, monitor=None, replica_id=None,
                journal=None, fault=None, num_blocks=32,
                ladder=None, warm=False):
    cache_cfg = default_cache_config(cfg, num_blocks=num_blocks,
                                     block_size=4)
    if ladder is None:
        ladder = BucketLadder(batch=(2, 4), pages=(2, 4))
    tp_ctx = None
    if tp:
        tp_ctx = TPContext(cfg, cache_cfg, tp)
    e = ServingEngine(weights, cfg, cache_cfg, ladder=ladder,
                      prefix_share=prefix_share, tp=tp_ctx,
                      device=device, monitor=monitor,
                      replica_id=replica_id, journal=journal,
                      fault=fault)
    if warm:
        e.warmup()
    return e


def make_requests(n, *, seed=3, tag="", max_new=4, min_len=1,
                  span=6):
    """Mixed-length prompts of min_len..min_len+span-1 tokens —
    sized so prompt + max_new always fits the test ladder's
    4-page x 4-token span."""
    rng = np.random.RandomState(seed)
    return [Request(rid=f"{tag}r{i}",
                    prompt=[int(t) for t in rng.randint(
                        0, VOCAB, min_len + rng.randint(span))],
                    max_new_tokens=max_new)
            for i in range(n)]


# ---------------------------------------------------------------------------
# tensor-parallel decode
# ---------------------------------------------------------------------------

class TestTensorParallel:
    def test_plan_budget_and_axes(self):
        plan = serving_tp_plan(2, num_layers=3)
        assert plan.budget() == {"psum": 6}
        ax = plan.axis("tensor")
        assert ax.size == 2 and ax.kind == "tensor"
        # weight patterns resolve against auditor-style paths
        assert plan.spec_for("in0.layers[0].qkv_k") == (None, "tensor")
        assert plan.spec_for("in0.layers[1].dense_k") == ("tensor",
                                                          None)
        assert plan.spec_for("in0.layers[0].fc2_k") == ("tensor", None)
        assert plan.spec_for("in0.wte") is None          # replicated
        assert plan.spec_for("in0.layers[0].dense_b") is None
        assert plan.spec_for("in1.k") == (None, None, "tensor")
        assert plan.spec_for("out0") == (None, None, "tensor")
        assert plan.spec_for("out2") == ()

    def test_context_validation(self, smoke_weights):
        cfg, _, _ = smoke_weights
        cc = default_cache_config(cfg, num_blocks=8, block_size=4)
        with pytest.raises(ValueError, match="tp 1 must be >= 2"):
            TPContext(cfg, cc, 1)
        with pytest.raises(ValueError, match="not divisible"):
            TPContext(cfg, cc, 3)               # 4 heads % 3
        other = default_cache_config(
            ServingModelConfig(vocab_size=VOCAB, hidden_size=64,
                               num_heads=8, num_layers=LAYERS,
                               max_seq=MAX_SEQ),
            num_blocks=8, block_size=4)
        with pytest.raises(ValueError, match="head geometry"):
            TPContext(cfg, other, 2)

    def test_tp_breaks_head_packing_rejected(self):
        # d=64 packs head PAIRS: 2 heads/shard is the floor — tp that
        # leaves one head per shard must be rejected, not mis-laid-out
        from apex_tpu.ops.flash_decode import use_decode_head_packing

        cfg = ServingModelConfig(vocab_size=VOCAB, hidden_size=256,
                                 num_heads=4, num_layers=1,
                                 max_seq=MAX_SEQ)
        cc = default_cache_config(cfg, num_blocks=8, block_size=4)
        if not use_decode_head_packing(4, 64):
            pytest.skip("head packing disabled in this environment")
        TPContext(cfg, cc, 2)                   # 2 heads/shard: fine
        with pytest.raises(ValueError, match="packing"):
            TPContext(cfg, cc, 4)               # 1 head/shard: breaks

    def test_tp_rejects_draft(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        cc = default_cache_config(cfg, num_blocks=16, block_size=4)
        tp = TPContext(cfg, cc, 2)
        with pytest.raises(ValueError, match="speculative"):
            ServingEngine(weights, cfg, cc, tp=tp, speculate_k=2,
                          draft_weights=weights, draft_cfg=cfg,
                          ladder=BucketLadder(batch=(2,), pages=(2,)))

    def test_tp_rejects_device_combo(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        cc = default_cache_config(cfg, num_blocks=16, block_size=4)
        tp = TPContext(cfg, cc, 2)
        with pytest.raises(ValueError, match="not both"):
            ServingEngine(weights, cfg, cc, tp=tp,
                          device=jax.devices()[0],
                          ladder=BucketLadder(batch=(2,), pages=(2,)))

    def test_tp_greedy_token_identical(self, smoke_weights):
        """The acceptance bar: tp=2 greedy output == single-chip,
        token for token, across mixed-length requests and bucket
        changes."""
        cfg, weights, _ = smoke_weights
        base = make_engine(cfg, weights)
        for r in make_requests(5, seed=11):
            base.submit(r)
        base.run()
        want = {q.rid: q.out_tokens for q in base.done}
        tpe = make_engine(cfg, weights, tp=2)
        for r in make_requests(5, seed=11):
            tpe.submit(r)
        s = tpe.run()
        got = {q.rid: q.out_tokens for q in tpe.done}
        assert got == want
        assert s.requests_done == 5

    def test_tp_swap_keeps_ladder(self, smoke_weights):
        cfg, weights, weights2 = smoke_weights
        e = make_engine(cfg, weights, tp=2,
                        ladder=BucketLadder(batch=(2,), pages=(2,)),
                        warm=True)
        for r in make_requests(2, seed=5, max_new=2):
            e.submit(r)
        s1 = e.run()
        e.swap_weights(weights2)
        for r in make_requests(2, seed=5, max_new=2):
            e.submit(r)
        s2 = e.run()
        assert s2.compiles == s1.compiles       # zero new compiles


# ---------------------------------------------------------------------------
# KV export/import (the disaggregation wire format)
# ---------------------------------------------------------------------------

class TestKVTransfer:
    @pytest.mark.parametrize("kv_dtype", ["model", "int8"])
    def test_gather_scatter_roundtrip_bitwise(self, kv_dtype):
        cc = KVCacheConfig(num_layers=2, num_heads=2, head_dim=8,
                           num_blocks=8, block_size=4,
                           kv_dtype=kv_dtype)
        src = init_cache(cc)
        key = jax.random.PRNGKey(0)
        fill = jax.random.normal(key, cc.kv_shape, jnp.float32) \
            .astype(cc.storage_dtype)
        src = src._replace(k=fill, v=fill * 2 if kv_dtype != "int8"
                           else fill)
        if cc.quantized:
            sc = jax.random.uniform(key, cc.scale_shape, jnp.float32)
            src = src._replace(k_scale=sc, v_scale=sc * 0.5)
        blocks = jnp.asarray([3, 1, 5], jnp.int32)
        k, v, ks, vs = gather_cache_blocks(src, blocks)
        assert k.shape == (2, 3) + cc.kv_shape[2:]
        dst = scatter_cache_blocks(init_cache(cc), k, v, ks, vs,
                                   jnp.asarray([2, 4, 6], jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(dst.k[:, 2]), np.asarray(src.k[:, 3]))
        np.testing.assert_array_equal(
            np.asarray(dst.v[:, 6]), np.asarray(src.v[:, 5]))
        if cc.quantized:
            np.testing.assert_array_equal(
                np.asarray(dst.k_scale[:, 4]),
                np.asarray(src.k_scale[:, 1]))

    def test_register_external_parks_idle_and_admits_warm(self):
        cc = KVCacheConfig(num_layers=1, num_heads=2, head_dim=8,
                           num_blocks=8, block_size=4)
        mgr = KVCacheManager(cc, prefix_sharing=True)
        prompt = list(range(10))                # 2 full + 1 partial
        blocks = mgr.register_external(prompt, 3)
        assert len(blocks) == 3
        assert mgr.idle_blocks == 3 and mgr.free_blocks == 4
        # second import of the same prompt: already resident
        assert mgr.register_external(prompt, 3) is None
        m = mgr.match_prefix(prompt)
        assert m.warm and m.tokens == len(prompt) - 1 and m.cow
        assert mgr.resident_prefix(prompt) == blocks

    def test_register_external_page_mismatch(self):
        cc = KVCacheConfig(num_layers=1, num_heads=2, head_dim=8,
                           num_blocks=8, block_size=4)
        mgr = KVCacheManager(cc, prefix_sharing=True)
        with pytest.raises(ValueError, match="block_size mismatch"):
            mgr.register_external(list(range(10)), 2)

    def test_register_external_needs_sharing(self):
        cc = KVCacheConfig(num_layers=1, num_heads=2, head_dim=8,
                           num_blocks=8, block_size=4)
        with pytest.raises(ValueError, match="prefix_sharing"):
            KVCacheManager(cc).register_external([1, 2], 1)

    def test_transfer_geometry_mismatch(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        a = make_engine(cfg, weights, prefix_share=True)
        b = make_engine(cfg, weights, prefix_share=True,
                        num_blocks=32)
        b.cache_cfg = default_cache_config(cfg, num_blocks=32,
                                           block_size=8)
        with pytest.raises(ValueError, match="incompatible"):
            transfer_prefix(a, b, [1, 2, 3])

    def test_disaggregated_handoff_warm_and_identical(
            self, smoke_weights):
        """The tentpole-3 proof: prefill on engine A, KV shipped to
        engine B, B's admission lands warm (prefix_hit_tokens > 0)
        and B's output is token-identical to a colocated serve."""
        cfg, weights, _ = smoke_weights
        reqs = make_requests(3, seed=9, tag="d", min_len=5)
        solo = make_engine(cfg, weights, prefix_share=True)
        for r in make_requests(3, seed=9, tag="d", min_len=5):
            solo.submit(r)
        solo.run()
        want = {q.rid: q.out_tokens for q in solo.done}

        pf = make_engine(cfg, weights, prefix_share=True)
        dec = make_engine(cfg, weights, prefix_share=True)
        for r in reqs:
            probe = Request(rid=f"pf:{r.rid}", prompt=list(r.prompt),
                            max_new_tokens=1)
            pf.submit(probe)
        pf.run()
        for r in reqs:
            shipped = transfer_prefix(pf, dec, r.prompt)
            assert shipped is not None and shipped > 0
            dec.submit(r)
        s = dec.run()
        got = {q.rid: q.out_tokens for q in dec.done}
        assert got == want
        assert s.warm_prefix_admissions == 3
        assert s.prefix_hit_tokens > 0

    def test_transfer_unresident_returns_none(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        a = make_engine(cfg, weights, prefix_share=True)
        b = make_engine(cfg, weights, prefix_share=True)
        assert transfer_prefix(a, b, [1, 2, 3, 4]) is None


# ---------------------------------------------------------------------------
# router scoring + snapshots
# ---------------------------------------------------------------------------

class TestRouter:
    def test_snapshot_fields(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        e = make_engine(cfg, weights, prefix_share=True,
                        replica_id="rX")
        snap = e.router_snapshot()
        for key in ("replica", "free_blocks", "available_blocks",
                    "reserved_blocks", "queue_depth", "active",
                    "prefilling", "shed_engaged", "warm_prefix_keys",
                    "gauges"):
            assert key in snap, key
        assert snap["replica"] == "rX"
        assert snap["warm_prefix_keys"] == frozenset()
        # serve one request: its prompt pages register, keys appear
        for r in make_requests(1, seed=2, min_len=6):
            e.submit(r)
        e.run()
        assert len(e.router_snapshot()["warm_prefix_keys"]) > 0

    def test_validation(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        e1 = make_engine(cfg, weights)
        with pytest.raises(ValueError, match="at least one"):
            FleetRouter([])
        with pytest.raises(ValueError, match="duplicate"):
            FleetRouter([Replica("a", e1),
                         Replica("a", make_engine(cfg, weights))])
        with pytest.raises(ValueError, match="serve-role"):
            FleetRouter([Replica("p", make_engine(
                cfg, weights, prefix_share=True), role="prefill")])
        with pytest.raises(ValueError, match="role"):
            Replica("x", e1, role="frontend")
        with pytest.raises(ValueError, match="prefix_share"):
            FleetRouter([Replica("s", make_engine(cfg, weights)),
                         Replica("p", make_engine(cfg, weights),
                                 role="prefill")])

    def test_round_robin_cycles(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        router = FleetRouter(
            [Replica("a", make_engine(cfg, weights)),
             Replica("b", make_engine(cfg, weights))],
            policy="round_robin")
        picks = [router.route(r).replica_id
                 for r in make_requests(4, seed=1)]
        assert picks == ["a", "b", "a", "b"]

    def test_gauges_policy_balances_backlog(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        ra = Replica("a", make_engine(cfg, weights))
        rb = Replica("b", make_engine(cfg, weights))
        router = FleetRouter([ra, rb])
        for r in make_requests(6, seed=4):
            router.submit(r)
        qa = len(ra.engine.queue)
        qb = len(rb.engine.queue)
        assert qa == 3 and qb == 3, (qa, qb)

    def test_sticky_warm_routing(self, smoke_weights):
        """A prompt resident in replica A's prefix index routes to A
        even when B has identical headroom."""
        cfg, weights, _ = smoke_weights
        ra = Replica("a", make_engine(cfg, weights,
                                      prefix_share=True))
        rb = Replica("b", make_engine(cfg, weights,
                                      prefix_share=True))
        router = FleetRouter([ra, rb])
        warm_req = make_requests(1, seed=8, min_len=9)[0]
        ra.engine.submit(Request(rid="seed", prompt=list(
            warm_req.prompt), max_new_tokens=2))
        ra.engine.run()
        assert router.route(warm_req).replica_id == "a"
        assert router.sticky_routes == 1
        # an unrelated prompt still balances away from a's backlog
        cold = Request(rid="cold", prompt=[63, 62, 61, 60],
                       max_new_tokens=2)
        assert router.route(cold).replica_id in ("a", "b")

    def test_unroutable_when_all_stopped(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        r = Replica("a", make_engine(cfg, weights))
        router = FleetRouter([r])
        r.routable = False
        with pytest.raises(RuntimeError, match="no routable"):
            router.route(make_requests(1)[0])

    def test_gauges_router_snapshot(self):
        from apex_tpu.serving import EngineGauges

        g = EngineGauges(every=4)
        g.observe(0, free_blocks=7, used_blocks=3)
        snap = g.router_snapshot()
        assert snap["free_blocks"] == 7
        assert snap["used_blocks_high_water"] == 3
        # reading the snapshot does NOT advance the cadence window
        assert g.observe(1, free_blocks=6, used_blocks=4) is None


# ---------------------------------------------------------------------------
# fleet drive loops: stepped, swap, crash replay, threads
# ---------------------------------------------------------------------------

class TestFleetServe:
    def test_stepped_completes_all(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        router = FleetRouter([
            Replica("r0", make_engine(cfg, weights)),
            Replica("r1", make_engine(cfg, weights))])
        s = router.serve(make_requests(6, seed=21))
        assert s.requests_done == 6
        assert s.lost_requests == 0
        assert s.requests_submitted == 6
        assert s.replicas == 2 and not s.threaded
        assert set(s.per_replica) == {"r0", "r1"}

    def test_rolling_swap_zero_lost_and_weights_replaced(
            self, smoke_weights):
        cfg, weights, weights2 = smoke_weights
        mk = lambda: make_engine(cfg, weights, warm=True)
        router = FleetRouter([Replica("r0", mk()),
                              Replica("r1", mk())])
        reqs = make_requests(6, seed=31, max_new=6)
        s = router.serve(reqs, swap_after=2, swap_weights=weights2)
        assert s.swaps == 2
        assert s.lost_requests == 0
        assert s.requests_done == 6
        # the swap really replaced the model: a fresh request now
        # decodes under weights2 — compare against a weights2 engine
        probe = make_requests(1, seed=77, min_len=6)[0]
        ref = make_engine(cfg, weights2)
        ref.submit(Request(rid=probe.rid, prompt=list(probe.prompt),
                           max_new_tokens=probe.max_new_tokens))
        ref.run()
        want = {q.rid: q.out_tokens for q in ref.done}
        target = router.serve([probe])
        assert target.lost_requests == 0
        got = {q.rid: q.out_tokens
               for r in router.serve_replicas
               for q in r.engine.done if q.rid == probe.rid}
        assert got == want
        # and the compiled ladder survived: no replica recompiled
        for r in router.serve_replicas:
            assert all(v == 1 for v in r.engine._compiles.values())

    def test_rolling_requant_swap_bf16_to_int8(self, smoke_weights):
        # the Q8 rollout path (ISSUE-16): a rolling swap hands each
        # replica an int8-quantized pytree; the treedef changes, so the
        # recompile is charged to the drained swap window — steady
        # state afterwards must stay zero-recompile, with zero lost
        # requests during the roll
        from apex_tpu.ops.quant_matmul import (is_quantized_weights,
                                               quantize_weights)
        cfg, weights, _ = smoke_weights
        qweights = quantize_weights(weights)
        mk = lambda: make_engine(cfg, weights, warm=True)
        router = FleetRouter([Replica("r0", mk()),
                              Replica("r1", mk())])
        reqs = make_requests(6, seed=41, max_new=6)
        s = router.serve(reqs, swap_after=2, swap_weights=qweights)
        assert s.swaps == 2
        assert s.lost_requests == 0
        assert s.requests_done == 6
        for r in router.serve_replicas:
            assert is_quantized_weights(r.engine.weights)
        # steady state after the swap: more traffic, no new compiles
        before = {r.replica_id: dict(r.engine._compiles)
                  for r in router.serve_replicas}
        more = router.serve(make_requests(4, seed=43))
        assert more.lost_requests == 0
        assert more.requests_done - s.requests_done == 4
        for r in router.serve_replicas:
            assert dict(r.engine._compiles) == before[r.replica_id]

    def test_swap_requires_idle(self, smoke_weights):
        cfg, weights, weights2 = smoke_weights
        e = make_engine(cfg, weights)
        e.submit(make_requests(1)[0])
        with pytest.raises(RuntimeError, match="busy"):
            e.swap_weights(weights2)

    def test_swap_shape_mismatch(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        e = make_engine(cfg, weights)
        bad = weights._replace(wte=jnp.zeros((VOCAB, HIDDEN * 2),
                                             jnp.float32))
        with pytest.raises(ValueError, match="swap_weights leaf"):
            e.swap_weights(bad)

    def test_crash_replay_in_fleet(self, smoke_weights, tmp_path):
        from apex_tpu.resilience import parse_fault

        cfg, weights, _ = smoke_weights
        j0 = RequestJournal(str(tmp_path / "r0.journal.jsonl"))
        router = FleetRouter([
            Replica("r0", make_engine(cfg, weights, journal=j0),
                    journal=j0, fault=parse_fault("crash@2")),
            Replica("r1", make_engine(cfg, weights))])
        s = router.serve(make_requests(8, seed=41, max_new=6))
        assert s.restarts == 1
        assert s.replayed_requests > 0
        assert s.lost_requests == 0
        assert s.requests_done == 8

    def test_unjournaled_crash_propagates(self, smoke_weights):
        from apex_tpu.resilience import parse_fault
        from apex_tpu.resilience.faults import InjectedCrash

        cfg, weights, _ = smoke_weights
        router = FleetRouter([
            Replica("r0", make_engine(cfg, weights),
                    fault=parse_fault("crash@1"))])
        with pytest.raises(InjectedCrash):
            router.serve(make_requests(2, seed=1, max_new=4))

    def test_threaded_completes_all(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        devs = jax.devices()
        router = FleetRouter([
            Replica("t0", make_engine(cfg, weights,
                                      device=devs[0])),
            Replica("t1", make_engine(cfg, weights,
                                      device=devs[1 % len(devs)]))])
        s = router.serve_threaded(make_requests(6, seed=51))
        assert s.requests_done == 6 and s.lost_requests == 0
        assert s.threaded
        # shares balanced by the planned-backlog scoring
        done = {r.replica_id: len(r.engine.done)
                for r in router.serve_replicas}
        assert done["t0"] == 3 and done["t1"] == 3, done

    def test_threaded_rejects_disagg(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        router = FleetRouter([
            Replica("s", make_engine(cfg, weights,
                                     prefix_share=True)),
            Replica("p", make_engine(cfg, weights,
                                     prefix_share=True),
                    role="prefill")])
        with pytest.raises(ValueError, match="stepped"):
            router.serve_threaded(make_requests(2))

    def test_disaggregated_stepped(self, smoke_weights):
        cfg, weights, _ = smoke_weights
        router = FleetRouter([
            Replica("d0", make_engine(cfg, weights,
                                      prefix_share=True)),
            Replica("pf0", make_engine(cfg, weights,
                                       prefix_share=True),
                    role="prefill")])
        s = router.serve(make_requests(4, seed=61, min_len=5))
        assert s.handoffs > 0
        assert s.prefix_hit_tokens > 0
        assert s.warm_prefix_admissions > 0
        assert s.lost_requests == 0 and s.requests_done == 4


# ---------------------------------------------------------------------------
# replica stamping + fleet trace aggregation
# ---------------------------------------------------------------------------

class TestFleetObservability:
    def test_replica_monitor_stamps_events(self, smoke_weights):
        from apex_tpu.monitor import StepMonitor

        cfg, weights, _ = smoke_weights
        sink = MemorySink()
        mon = StepMonitor(sink, close_sink=False)
        e = make_engine(cfg, weights, monitor=mon, replica_id="r7")
        for r in make_requests(2, seed=71):
            e.submit(r)
        e.run()
        srv = [ev for ev in sink.events if ev.kind == "serving"]
        assert srv and all(ev.attrs.get("replica") == "r7"
                           for ev in srv)
        # explicit replica attrs win over the stamp
        e.monitor.event("fleet", "probe", replica="other")
        probe = [ev for ev in sink.events if ev.name == "probe"][0]
        assert probe.attrs["replica"] == "other"

    def test_check_serve_trace_fleet(self, smoke_weights, tmp_path):
        from apex_tpu.monitor import JsonlSink, StepMonitor
        from apex_tpu.monitor.tracing import check_serve_trace

        cfg, weights, _ = smoke_weights
        paths = []
        for i in range(2):
            path = str(tmp_path / f"serve-r{i}.jsonl")
            paths.append(path)
            mon = StepMonitor(JsonlSink(path))
            e = make_engine(cfg, weights, monitor=mon,
                            replica_id=f"r{i}")
            for r in make_requests(2, seed=80 + i, tag=f"x{i}"):
                e.submit(r)
            e.run()
            mon.close()
        assert check_serve_trace(paths) == []
        # a rid living on two replicas must fail the fleet check
        dup = str(tmp_path / "dup.jsonl")
        with open(paths[0]) as f, open(dup, "w") as g:
            for line in f:
                ev = json.loads(line)
                if ev.get("attrs", {}).get("replica") == "r0":
                    ev["attrs"]["replica"] = "r9"
                g.write(json.dumps(ev) + "\n")
        failures = check_serve_trace([paths[0], dup])
        assert any("lifecycle events on 2 replicas" in f
                   for f in failures), failures

    def test_fleet_summary_digest(self, smoke_weights, tmp_path):
        from apex_tpu.monitor.summary import load_events, summarize

        cfg, weights, _ = smoke_weights
        path = str(tmp_path / "fleet.jsonl")
        from apex_tpu.monitor import JsonlSink, StepMonitor

        mon = StepMonitor(JsonlSink(path))
        router = FleetRouter(
            [Replica("r0", make_engine(cfg, weights, monitor=mon,
                                       replica_id="r0")),
             Replica("r1", make_engine(cfg, weights, monitor=mon,
                                       replica_id="r1"))],
            monitor=mon)
        router.serve(make_requests(4, seed=91))
        mon.close()
        events, malformed = load_events(path)
        digest = summarize(events, malformed)["serving"]
        reps = digest["replicas"]
        assert set(reps) == {"r0", "r1"}
        assert all(v["submitted"] == v["terminal"]
                   for v in reps.values())
        assert digest["fleet"]["routed"] == 4

    def test_fleet_flags_registered(self):
        from apex_tpu.analysis.flags import FLAGS, flag_value

        for name in ("APEX_TPU_SERVE_REPLICAS", "APEX_TPU_SERVE_TP",
                     "APEX_TPU_SERVE_DISAGGREGATE",
                     "APEX_TPU_SERVE_ROUTER"):
            assert name in FLAGS, name
        assert flag_value("APEX_TPU_SERVE_REPLICAS") == 1
        assert flag_value("APEX_TPU_SERVE_ROUTER") == "gauges"

    def test_prefix_chain_keys_shared_convention(self):
        cc = KVCacheConfig(num_layers=1, num_heads=2, head_dim=8,
                           num_blocks=8, block_size=4)
        mgr = KVCacheManager(cc, prefix_sharing=True)
        prompt = list(range(9))
        keys, pkey = prefix_chain_keys(prompt, 4)
        mkeys, mpkey = mgr._chain_keys(prompt)
        assert keys == mkeys and pkey == mpkey
        assert len(keys) == 2 and pkey is not None
