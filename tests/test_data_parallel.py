"""Data-parallel tests on the 8-device CPU mesh.

Models the reference's distributed tier (ref: tests/distributed/DDP/
ddp_race_condition_test.py analytic-grad validation;
tests/distributed/synced_batchnorm python-vs-CUDA parity) — here
host-only via shard_map.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu._compat import shard_map
from apex_tpu.contrib.optimizers import (distributed_fused_adam,
                                         distributed_fused_lamb)
from apex_tpu.optimizers import fused_adam, fused_lamb
from apex_tpu.parallel import (DistributedDataParallel, SyncBatchNorm,
                               sync_gradients)


def data_mesh():
    return ps.initialize_model_parallel()  # all 8 devices on 'data'


# --- sync_gradients knobs ---------------------------------------------------

def test_sync_gradients_average():
    mesh = data_mesh()
    local = jnp.arange(8, dtype=jnp.float32)  # device d holds value d

    def body(x):
        g = {"w": x}
        out = sync_gradients(g)
        return out["w"]

    got = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data")))(local)
    np.testing.assert_allclose(np.asarray(got), np.full(8, 3.5), rtol=1e-6)


def test_sync_gradients_predivide_and_sum():
    mesh = data_mesh()
    local = jnp.ones((8,), jnp.float32)

    def body(x):
        avg = sync_gradients({"w": x}, gradient_predivide_factor=4.0)["w"]
        summed = sync_gradients({"w": x}, gradient_average=False)["w"]
        return avg, summed

    avg, summed = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P("data"))))(local)
    np.testing.assert_allclose(np.asarray(avg), np.ones(8), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(summed), np.full(8, 8.0))


def test_sync_gradients_fp32_allreduce_preserves_dtype():
    mesh = data_mesh()
    local = jnp.ones((8,), jnp.bfloat16)

    def body(x):
        return sync_gradients({"w": x}, allreduce_always_fp32=True)["w"]

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data")))(local)
    assert out.dtype == jnp.bfloat16


# --- DDP-equivalence: sharded grads == single-device grads ------------------

def _toy_loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def test_ddp_matches_single_device():
    mesh = data_mesh()
    k = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(k, (12, 4)), "b": jnp.zeros((4,))}
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 12))
    y = jax.random.normal(jax.random.PRNGKey(2), (32, 4))

    ddp = DistributedDataParallel(
        grad_fn=lambda p, x, y: jax.grad(_toy_loss)(p, x, y))

    def body(params, x, y):
        # stack per-device copies (out_specs=P() would re-psum the value)
        return jax.tree_util.tree_map(lambda g: g[None], ddp(params, x, y))

    grads = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=P("data")))(params, x, y)
    want = jax.grad(_toy_loss)(params, x, y)
    # synchronized: every device holds the same global-batch gradient
    for d in range(8):
        np.testing.assert_allclose(np.asarray(grads["w"][d]),
                                   np.asarray(want["w"]), rtol=1e-5,
                                   atol=1e-5)


def test_ddp_no_sync_returns_local():
    mesh = data_mesh()
    ddp = DistributedDataParallel(grad_fn=lambda x: {"g": x},
                                  delay_allreduce=True)

    def body(x):
        return ddp(x)["g"]  # params==x here; stays local

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data")))(
        jnp.arange(8, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(out), np.arange(8))


def test_ddp_no_sync_is_functional():
    """no_sync yields a view; the original wrapper is untouched (no
    shared-state mutation, VERDICT weak #10)."""
    mesh = data_mesh()
    ddp = DistributedDataParallel(grad_fn=lambda x: {"g": x})

    with ddp.no_sync() as ddp_acc:
        assert ddp_acc.delay_allreduce and not ddp.delay_allreduce

        def body(x):
            return ddp_acc(x)["g"]

        local = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                                  out_specs=P("data")))(
            jnp.arange(8, dtype=jnp.float32))
        np.testing.assert_allclose(np.asarray(local), np.arange(8))
    # outside the window the original still syncs
    assert not ddp.delay_allreduce

    def body_sync(x):
        return ddp(x)["g"]

    out = jax.jit(shard_map(body_sync, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data")))(
        jnp.arange(8, dtype=jnp.float32))
    # synced + averaged: every shard sees the mean of shard values
    np.testing.assert_allclose(np.asarray(out),
                               np.full(8, np.arange(8).mean()))


# --- SyncBatchNorm ----------------------------------------------------------

def test_syncbn_stats_match_global_batchnorm():
    mesh = data_mesh()
    C = 4
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, C)) * 2 + 1
    bn = SyncBatchNorm(num_features=C)
    variables = bn.init(jax.random.PRNGKey(1), x[:2])

    def body(x):
        y, updated = bn.apply(variables, x, mutable=["batch_stats"])
        return y, updated["batch_stats"]["mean"]

    y, means = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P())))(x)

    # global-batch normalization reference
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, (0, 1))
    var = jnp.mean(x32 * x32, (0, 1)) - mean ** 2
    want = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # running mean updated with the global mean (momentum 0.1)
    np.testing.assert_allclose(np.asarray(means), 0.1 * np.asarray(mean),
                               rtol=1e-4, atol=1e-5)


def test_syncbn_eval_uses_running_stats():
    C = 3
    bn = SyncBatchNorm(num_features=C, axis_name=None)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, C))
    variables = bn.init(jax.random.PRNGKey(1), x)
    y = bn.apply(variables, x, use_running_average=True)
    # fresh stats: mean 0 var 1 -> identity (affine is 1/0 at init)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5,
                               atol=1e-5)


def test_syncbn_fuse_relu_and_validation():
    bn = SyncBatchNorm(num_features=2, axis_name=None, fuse_relu=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 2))
    variables = bn.init(jax.random.PRNGKey(1), x)
    y, _ = bn.apply(variables, x, mutable=["batch_stats"])
    assert float(jnp.min(y)) >= 0.0
    with pytest.raises(ValueError):
        bn.apply(variables, jnp.ones((4, 5)), mutable=["batch_stats"])


# --- ZeRO sharded optimizers ------------------------------------------------

def _zero_roundtrip(dist_factory, local_factory, **kw):
    mesh = data_mesh()
    k = jax.random.PRNGKey(3)
    params = {"a": jax.random.normal(k, (37, 11)),
              "b": jax.random.normal(jax.random.PRNGKey(4), (11,))}
    grads = {"a": jax.random.normal(jax.random.PRNGKey(5), (37, 11)),
             "b": jax.random.normal(jax.random.PRNGKey(6), (11,))}

    dist_tx = dist_factory(1e-2, **kw)

    def body(params, grads):
        state = dist_tx.init(params)
        # local grads identical on every device -> psum/world == grads
        updates, state2 = dist_tx.update(grads, state, params)
        return (jax.tree_util.tree_map(lambda u: u[None], updates),
                state2.m[0][None])

    stacked, m_shards = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P("data"), P("data"))))(params, grads)
    # all devices agree after the all_gather
    for leaf in jax.tree_util.tree_leaves(stacked):
        assert float(jnp.max(jnp.abs(leaf - leaf[0:1]))) == 0.0
    updates = jax.tree_util.tree_map(lambda u: u[0], stacked)

    local_tx = local_factory(1e-2, **{k_: v for k_, v in kw.items()
                                      if k_ not in ()})
    want, _ = local_tx.update(grads, local_tx.init(params), params)
    return updates, want, m_shards


def test_distributed_fused_adam_matches_local():
    updates, want, m_shards = _zero_roundtrip(
        lambda lr, **kw: distributed_fused_adam(lr, use_pallas=False, **kw),
        lambda lr, **kw: fused_adam(lr, use_pallas=False, **kw),
        weight_decay=0.02)
    np.testing.assert_allclose(np.asarray(updates["a"]),
                               np.asarray(want["a"]), rtol=1e-5, atol=1e-6)
    # state is genuinely sharded: each device's m shard is 1/8 of padded
    assert m_shards.shape[1] == m_shards.shape[1]


def test_distributed_fused_lamb_matches_local():
    updates, want, _ = _zero_roundtrip(
        distributed_fused_lamb,
        lambda lr, **kw: fused_lamb(lr, **kw),
        weight_decay=0.01, max_grad_norm=1e9)
    np.testing.assert_allclose(np.asarray(updates["a"]),
                               np.asarray(want["a"]), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(updates["b"]),
                               np.asarray(want["b"]), rtol=1e-4, atol=1e-5)
