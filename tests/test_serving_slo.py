"""Per-class SLO burn-rate tests (ISSUE-17): the multi-window trip
condition on a deterministic tick grid (fast blip alone never pages,
a long-decayed slow-window stain alone never pages, both together
trip exactly once per episode), recovery clearing the episode latch,
availability terminal classification (shed/deadline bad, preempted
clean), flag construction, and the engine integration — a forced
breach emits slo_objectives before exactly one slo_burn alarm, flips
health_state to slo_burning, and lands in ServeSummary + the
exporter's slo families.
"""
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor import Event, MemorySink, Watchdog
from apex_tpu.monitor.export import MetricsExporter
from apex_tpu.serving import (BucketLadder, Request, ServingEngine,
                              ServingModelConfig, SLObjective,
                              SLOTracker, default_cache_config,
                              extract_serving_weights)
from apex_tpu.testing.standalone_gpt import GPTModel


class StubMonitor:
    def __init__(self, watchdog=None):
        self.sink = MemorySink()
        self.watchdog = watchdog

    def event(self, kind, name, value=None, step=None, **attrs):
        self.sink.emit(Event(time=float(step or 0), step=step,
                             kind=kind, name=name, value=value,
                             attrs=attrs))


def _tracker(fast=8, slow=64, threshold=2.0, **obj):
    return SLOTracker([SLObjective(**obj)], fast_window=fast,
                      slow_window=slow, burn_threshold=threshold)


# ---------------------------------------------------------------------------
# objective declaration
# ---------------------------------------------------------------------------

class TestSLObjective:
    def test_dimensions_and_budgets(self):
        obj = SLObjective(ttft_p99_ms=200.0, itl_p99_ms=0.0,
                          availability=0.99)
        dims = {d: (thr, budget) for d, thr, budget
                in obj.dimensions()}
        # p99 objectives budget 1% violations by definition; the
        # availability budget is the complement of the target
        assert dims == {"ttft": (200.0, 0.01),
                        "availability": (0.99, pytest.approx(0.01))}
        assert obj.matches("p0") and obj.matches("p7")
        scoped = SLObjective(priority_class="p1", ttft_p99_ms=1.0)
        assert scoped.matches("p1") and not scoped.matches("p0")

    def test_all_zero_objective_disables_tracker(self):
        t = SLOTracker([SLObjective()])
        assert not t.enabled and t.evaluate(100) == []

    def test_from_flags(self, monkeypatch):
        for k in ("APEX_TPU_SLO_TTFT_P99_MS", "APEX_TPU_SLO_ITL_P99_MS",
                  "APEX_TPU_SLO_AVAILABILITY"):
            monkeypatch.delenv(k, raising=False)
        assert SLOTracker.from_flags() is None    # default: no tracker
        monkeypatch.setenv("APEX_TPU_SLO_TTFT_P99_MS", "150")
        monkeypatch.setenv("APEX_TPU_SLO_AVAILABILITY", "0.995")
        t = SLOTracker.from_flags()
        assert t is not None and t.enabled
        (obj,) = t.objectives
        assert obj.priority_class == "*"
        assert obj.ttft_p99_ms == 150.0
        assert obj.availability == 0.995
        assert obj.itl_p99_ms == 0.0


# ---------------------------------------------------------------------------
# burn-rate grid on a deterministic tick clock
# ---------------------------------------------------------------------------

class TestBurnRateGrid:
    def test_dual_window_trip_and_once_per_episode(self):
        t = _tracker(ttft_p99_ms=100.0)
        for tick in range(1, 9):
            t.record_ttft("p0", 500.0, tick)      # 8/8 over budget
        trs = t.evaluate(8)
        assert len(trs) == 1 and trs[0]["action"] == "burn"
        a = trs[0]
        assert a["priority_class"] == "*" and a["dimension"] == "ttft"
        # all-bad over a 1% budget: burn = (8/8)/0.01 = 100x
        assert a["burn_fast"] == pytest.approx(100.0)
        assert a["burn_slow"] == pytest.approx(100.0)
        assert a["n_fast"] == 8 and a["bad_fast"] == 8
        # the episode latches: still burning, no second transition
        t.record_ttft("p0", 500.0, 9)
        assert t.evaluate(9) == []
        assert t.episodes == 1 and t.burning == ["*/ttft"]

    def test_fast_blip_with_clean_slow_window_never_pages(self):
        t = _tracker(fast=8, slow=1024, ttft_p99_ms=100.0)
        # a long healthy history inside the slow window...
        for i in range(2000):
            t.record_ttft("p0", 10.0, 500)
        # ...then an all-bad fast window: burn_fast = 100x but the
        # slow window dilutes to (8/2008)/0.01 < 2x — no page
        for tick in range(993, 1001):
            t.record_ttft("p0", 500.0, tick)
        assert t.evaluate(1000) == []
        assert t.episodes == 0 and t.burning == []

    def test_stale_slow_stain_with_clean_fast_never_pages(self):
        t = _tracker(fast=8, slow=64, ttft_p99_ms=100.0)
        for tick in range(1, 9):
            t.record_ttft("p0", 500.0, tick)      # old stain
        for tick in range(20, 28):
            t.record_ttft("p0", 10.0, tick)       # fast window clean
        assert t.evaluate(27) == []
        assert t.episodes == 0
        # and once the stain ages past the slow window it is evicted
        # entirely — a later evaluation sees only clean samples
        t.record_ttft("p0", 10.0, 100)
        assert t.evaluate(100) == []
        assert t._samples[(0, "ttft")][0][0] > 100 - 64

    def test_recovery_clears_latch_and_allows_second_episode(self):
        t = _tracker(fast=8, slow=64, ttft_p99_ms=100.0)
        for tick in range(1, 9):
            t.record_ttft("p0", 500.0, tick)
        assert t.evaluate(8)[0]["action"] == "burn"
        # clean samples push the fast-window burn back under the
        # threshold -> exactly one recovered transition
        for tick in range(9, 17):
            t.record_ttft("p0", 10.0, tick)
        trs = t.evaluate(16)
        assert len(trs) == 1 and trs[0]["action"] == "recovered"
        assert t.burning == [] and t.recoveries == 1
        assert t.evaluate(17) == []               # recovery latched too
        # a fresh breach opens a SECOND episode
        for tick in range(80, 88):
            t.record_ttft("p0", 500.0, tick)
        assert t.evaluate(87)[0]["action"] == "burn"
        assert t.episodes == 2

    def test_availability_counts_shed_and_deadline_not_preempted(self):
        t = _tracker(fast=8, slow=64, availability=0.99)
        for i, term in enumerate(("shed", "deadline",
                                  "deadline_exceeded", "preempted",
                                  "finished", "finished", "finished",
                                  "finished")):
            t.record_terminal("p0", term, i + 1)
        trs = t.evaluate(8)
        # 3 of 8 bad over a 1% budget: burn = 37.5x on both windows
        assert len(trs) == 1 and trs[0]["action"] == "burn"
        assert trs[0]["dimension"] == "availability"
        assert trs[0]["bad_fast"] == 3
        assert trs[0]["burn_fast"] == pytest.approx(37.5)

    def test_class_scoped_objective_ignores_other_classes(self):
        t = _tracker(fast=8, slow=64, priority_class="p1",
                     itl_p99_ms=50.0)
        for tick in range(1, 9):
            t.record_itl("p0", 500.0, tick)       # wrong class
        assert t.evaluate(8) == []
        for tick in range(9, 17):
            t.record_itl("p1", 500.0, tick)
        trs = t.evaluate(16)
        assert len(trs) == 1 and trs[0]["action"] == "burn"
        assert trs[0]["priority_class"] == "p1"
        assert t.burning == ["p1/itl"]


# ---------------------------------------------------------------------------
# engine integration: forced breach end to end
# ---------------------------------------------------------------------------

def _engine(monitor, *, slo, exporter=None):
    model = GPTModel(
        vocab_size=32, hidden_size=16, num_layers=2,
        num_attention_heads=2, max_sequence_length=32,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    params = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = ServingModelConfig.from_model(
        model, prefill_flash=False, decode_attention="reference")
    weights = extract_serving_weights(params, cfg.num_layers)
    cache_cfg = default_cache_config(cfg, num_blocks=16, block_size=4)
    return ServingEngine(weights, cfg, cache_cfg,
                         ladder=BucketLadder(batch=(2, 4), pages=(3,)),
                         monitor=monitor, slo=slo, exporter=exporter)


class TestEngineIntegration:
    def _run(self, mon, *, slo, exporter=None, n=3):
        eng = _engine(mon, slo=slo, exporter=exporter)
        for i in range(n):
            eng.submit(Request(rid=f"r{i}", prompt=[3 + i, 7],
                               max_new_tokens=3))
        return eng, eng.run()

    def test_forced_breach_single_episode_chain(self):
        # a 1us TTFT objective: every real request breaches, so the
        # first evaluation after the first TTFT sample trips — and
        # ONLY once, however many ticks follow
        mon = StubMonitor()
        exp = MetricsExporter()
        slo = SLOTracker([SLObjective(ttft_p99_ms=0.001)])
        eng, summary = self._run(mon, slo=slo, exporter=exp)
        defs = mon.sink.by_name("slo_objectives")
        burns = mon.sink.by_name("slo_burn")
        assert len(defs) == 1 and len(burns) == 1
        assert burns[0].kind == "alarm"
        # the definition event precedes the burn (trace_check pairs
        # them): same log, earlier position
        evs = list(mon.sink.events)
        assert evs.index(defs[0]) < evs.index(burns[0])
        a = burns[0].attrs
        assert a["dimension"] == "ttft" and a["burn_fast"] >= 2.0
        assert summary.slo_burn_episodes == 1
        assert summary.slo_recoveries == 0
        assert summary.slo_burning == ["*/ttft"]
        # health + exporter surfaces agree with the summary
        h = eng.health_state()
        assert h["status"] == "slo_burning" and not h["ok"]
        ok, payload = exp.healthz()
        assert not ok and payload["slo_burning"] == ["*/ttft"]
        samples = eng.export_registry().samples()
        assert samples["apex_tpu_slo_burn_episodes_total"] == {(): 1.0}
        assert samples["apex_tpu_slo_burning"] == {(): 1.0}

    def test_burn_routes_through_watchdog_alarm_machinery(self):
        sink = MemorySink()
        wd = Watchdog(sink, stall_timeout=1e9)
        mon = StubMonitor(watchdog=wd)
        mon.sink = sink
        slo = SLOTracker([SLObjective(ttft_p99_ms=0.001)])
        self._run(mon, slo=slo)
        burns = sink.by_name("slo_burn")
        assert len(burns) == 1 and burns[0].kind == "alarm"

    def test_generous_objective_stays_quiet(self):
        mon = StubMonitor()
        slo = SLOTracker([SLObjective(ttft_p99_ms=600000.0)])
        eng, summary = self._run(mon, slo=slo)
        assert mon.sink.by_name("slo_burn") == []
        assert summary.slo_burn_episodes == 0
        assert eng.health_state()["status"] == "ok"
        # the definition event still lands (the schema is logged even
        # for a quiet run — dashboards need the objectives)
        assert len(mon.sink.by_name("slo_objectives")) == 1

    def test_no_tracker_costs_nothing(self):
        mon = StubMonitor()
        eng, summary = self._run(mon, slo=None)
        assert eng.slo is None
        assert mon.sink.by_kind("slo") == []
        assert summary.slo_burn_episodes == 0
