"""Compiled-graph auditor tests (apex_tpu.analysis.hlo).

Per-rule synthetic fixtures — a knowably-donatable jit, a deliberate
bf16->f32 upcast, a psum added to a shard_map body, a forced host
callback — each asserting the exact rule and provenance, plus the
repo self-check: the committed tools/hlo_baseline.json must be
current against fresh lowerings of every registered entry point
(the conftest provides the 8-device host-platform mesh the multichip
entries need, same as tools/ci.sh step 8).
"""
import dataclasses
import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.analysis import hlo
from apex_tpu.testing import entry_points as eps

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry(name, build, **kw):
    return eps.EntryPoint(name=name, build=build, **kw)


def _audit(ep):
    from pathlib import Path

    return hlo._audit_one(ep.name, ep, Path(REPO))


# ---------------------------------------------------------------------------
# APX601 — missed donation
# ---------------------------------------------------------------------------

class TestDonation:
    def _build(self, donate):
        x = jnp.arange(4096, dtype=jnp.float32)

        def step(x):
            return x * 1.5 + 1.0

        fn = (functools.partial(jax.jit, donate_argnums=(0,))(step)
              if donate else jax.jit(step))
        return fn, (x,)

    def test_undonated_dead_arg_fires_apx601(self):
        ep = _entry("fixture_undonated",
                    lambda: self._build(donate=False), dead_args=(0,))
        audit = _audit(ep)
        rules = [f.rule for f in audit.findings]
        assert rules == ["APX601"]
        f = audit.findings[0]
        assert f.symbol == "arg0"
        assert "16384 bytes" in f.message
        assert audit.donated == {}

    def test_donated_arg_is_clean(self):
        ep = _entry("fixture_donated",
                    lambda: self._build(donate=True), dead_args=(0,))
        audit = _audit(ep)
        assert audit.findings == []
        assert 0 in audit.donated

    def test_live_arg_not_flagged(self):
        # same undonated jit, but the registry says the caller keeps
        # the buffer — donation would be wrong, not missing
        ep = _entry("fixture_live",
                    lambda: self._build(donate=False), dead_args=())
        assert _audit(ep).findings == []

    def test_tiny_buffers_ignored(self):
        def build():
            s = jnp.float32(2.0)  # 4 bytes: donation saves nothing
            return jax.jit(lambda s: s * 2.0), (s,)

        ep = _entry("fixture_tiny", build, dead_args=(0,))
        assert _audit(ep).findings == []

    def test_stablehlo_alias_parsing(self):
        fn, args = self._build(donate=True)
        text = fn.lower(*args).as_text()
        assert hlo._donated_args(text) == {0: 0}


# ---------------------------------------------------------------------------
# APX602 — silent dtype promotion
# ---------------------------------------------------------------------------

class TestPromotion:
    def _build_upcast(self):
        x = jnp.ones((256, 128), jnp.bfloat16)

        def f(x):
            y = x.astype(jnp.float32) * 2.0   # the deliberate upcast
            return y.astype(jnp.bfloat16) + x

        return jax.jit(f), (x,)

    def test_deliberate_upcast_fires_apx602_with_provenance(self):
        ep = _entry("fixture_upcast", self._build_upcast, policy="O5")
        audit = _audit(ep)
        apx602 = [f for f in audit.findings if f.rule == "APX602"]
        assert len(apx602) == 1
        f = apx602[0]
        assert f.path == "tests/test_analysis_hlo.py"
        assert f.line > 0
        assert "bfloat16->float32" in f.message
        assert f.symbol.startswith("fixture_upcast.f.")

    def test_policy_gate(self):
        # the same graph under a non-low-precision policy tag is not
        # a promotion hazard — APX602 is an O4/O5 rule
        ep = _entry("fixture_upcast_o2", self._build_upcast,
                    policy="O2")
        assert [f for f in _audit(ep).findings
                if f.rule == "APX602"] == []

    def test_sanctioned_region_exempt(self):
        ep = _entry("fixture_upcast_ok", self._build_upcast,
                    policy="O5",
                    allow_upcast=("tests/test_analysis_hlo.py",))
        assert [f for f in _audit(ep).findings
                if f.rule == "APX602"] == []


# ---------------------------------------------------------------------------
# APX603 — collective census
# ---------------------------------------------------------------------------

class TestCensus:
    def _build_psum(self, with_extra=False):
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu._compat import shard_map

        mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
        x = jnp.ones((64, 128), jnp.float32)

        def body(x):
            y = jax.lax.psum(x, "d")
            if with_extra:
                y = y + jax.lax.all_gather(x, "d").sum(0)
            return y

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("d"),
                               out_specs=P(), check_vma=False))
        return fn, (x,)

    def test_psum_in_shard_map_counted_with_bytes(self):
        ep = _entry("fixture_psum", self._build_psum)
        audit = _audit(ep)
        census = audit.census()
        assert "psum" in census
        assert census["psum"]["count"] == 1
        # per-shard (8, 128) fp32 = 4096 bytes moved per step
        assert census["psum"]["bytes_per_step"] == 8 * 128 * 4
        op = [o for o in audit.collectives if o.kind == "psum"][0]
        assert op.path == "tests/test_analysis_hlo.py"
        assert op.function == "body"

    def test_new_collective_kind_fails_diff(self):
        ep = _entry("fixture_psum2",
                    lambda: self._build_psum(with_extra=True))
        audit = _audit(ep)
        base_row = {"collectives": {"psum": audit.census()["psum"]},
                    "peak_live_bytes": audit.peak_live_bytes}
        findings = hlo._census_findings("fixture_psum2", audit,
                                        base_row)
        kinds = {f.symbol for f in findings if f.rule == "APX603"}
        assert "all_gather.new" in kinds
        new = [f for f in findings if f.symbol == "all_gather.new"][0]
        assert "tests/test_analysis_hlo.py" in new.message  # provenance

    def test_byte_growth_and_shrink_gated_at_10pct(self):
        ep = _entry("fixture_psum3", self._build_psum)
        audit = _audit(ep)
        row = audit.baseline_row()
        ok = json.loads(json.dumps(row))
        ok["collectives"]["psum"]["bytes_per_step"] = int(
            audit.census()["psum"]["bytes_per_step"] / 1.05)  # +5%
        assert [f for f in hlo._census_findings("e", audit, ok)
                if f.rule == "APX603"] == []
        grown = json.loads(json.dumps(row))
        grown["collectives"]["psum"]["bytes_per_step"] = int(
            audit.census()["psum"]["bytes_per_step"] / 1.5)  # +50%
        fs = [f for f in hlo._census_findings("e", audit, grown)
              if f.rule == "APX603"]
        assert any("grew >10%" in f.message for f in fs)
        shrunk = json.loads(json.dumps(row))
        shrunk["collectives"]["psum"]["bytes_per_step"] = int(
            audit.census()["psum"]["bytes_per_step"] * 2)
        fs = [f for f in hlo._census_findings("e", audit, shrunk)
              if f.rule == "APX603"]
        assert any("shrank >10%" in f.message for f in fs)

    def test_scan_body_collectives_priced_per_step(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu._compat import shard_map

        mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
        x = jnp.ones((64, 128), jnp.float32)

        def body(x):
            def it(c, _):
                return c + jax.lax.psum(x, "d"), ()

            out, _ = jax.lax.scan(it, jnp.zeros_like(x), None,
                                  length=5)
            return out

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("d"),
                               out_specs=P("d"), check_vma=False))
        ep = _entry("fixture_scan_psum", lambda: (fn, (x,)))
        census = _audit(ep).census()
        assert census["psum"]["count"] == 5
        assert census["psum"]["bytes_per_step"] == 5 * 8 * 128 * 4


# ---------------------------------------------------------------------------
# APX604 — host transfer in the compiled graph
# ---------------------------------------------------------------------------

class TestHostTransfer:
    def test_io_callback_fires_apx604(self):
        from jax.experimental import io_callback

        x = jnp.ones((128,), jnp.float32)

        def f(x):
            # the forced device->host round trip: XLA services this
            # callback from the host on every execution
            io_callback(lambda a: None, None, x)
            return x * 2.0

        ep = _entry("fixture_callback", lambda: (jax.jit(f), (x,)))
        audit = _audit(ep)
        apx604 = [f for f in audit.findings if f.rule == "APX604"]
        assert len(apx604) == 1
        assert apx604[0].path == "tests/test_analysis_hlo.py"
        assert "io_callback" in apx604[0].message

    def test_debug_print_fires_apx604(self):
        x = jnp.ones((128,), jnp.float32)

        def f(x):
            jax.debug.print("x0 {}", x[0])
            return x * 2.0

        ep = _entry("fixture_debug", lambda: (jax.jit(f), (x,)))
        assert any(f.rule == "APX604" for f in _audit(ep).findings)

    def test_clean_graph_has_no_apx604(self):
        x = jnp.ones((128,), jnp.float32)
        ep = _entry("fixture_clean",
                    lambda: (jax.jit(lambda x: x * 2.0), (x,)))
        assert _audit(ep).findings == []


# ---------------------------------------------------------------------------
# APX605 — peak-live-memory estimate
# ---------------------------------------------------------------------------

class TestPeakMemory:
    def test_known_program_exact_bytes(self):
        # x (4 KiB) live at entry; y = x*2 allocates 4 KiB (peak 8);
        # z = y + x allocates 4 KiB while x and y are still live ->
        # peak 12 KiB
        def f(x):
            y = x * 2.0
            return y + x

        closed = jax.make_jaxpr(f)(jnp.ones((1024,), jnp.float32))
        assert hlo.peak_live_bytes(closed.jaxpr) == 3 * 4096

    def test_freeing_lowers_the_peak(self):
        # a chain frees each intermediate after its single use: peak
        # is input + two live values, never all four
        def chain(x):
            a = x * 2.0
            b = a * 2.0
            c = b * 2.0
            return c

        closed = jax.make_jaxpr(chain)(jnp.ones((1024,), jnp.float32))
        assert hlo.peak_live_bytes(closed.jaxpr) == 2 * 4096

    def test_pjit_inner_peak_counted(self):
        # the same chain jitted: the walk must descend into the pjit
        # call and see the inner liveness, not price the call as one
        # opaque 4 KiB -> 4 KiB op
        @jax.jit
        def inner(x):
            a = x * 2.0
            b = a + x       # x + a + b live -> 12 KiB inside
            return b * 2.0

        closed = jax.make_jaxpr(lambda x: inner(x))(
            jnp.ones((1024,), jnp.float32))
        assert hlo.peak_live_bytes(closed.jaxpr) >= 3 * 4096

    def test_drift_gate(self):
        def f(x):
            return x * 2.0

        ep = _entry("fixture_mem", lambda: (jax.jit(f),
                                            (jnp.ones((1024,)),)))
        audit = _audit(ep)
        row = audit.baseline_row()
        assert hlo._census_findings("e", audit, row) == []
        small = dict(row, peak_live_bytes=row["peak_live_bytes"] // 2)
        fs = hlo._census_findings("e", audit, small)
        assert [f.rule for f in fs] == ["APX605"]
        assert "grew >10%" in fs[0].message
        big = dict(row, peak_live_bytes=row["peak_live_bytes"] * 2)
        fs = hlo._census_findings("e", audit, big)
        assert [f.rule for f in fs] == ["APX605"]
        assert "shrank >10%" in fs[0].message


# ---------------------------------------------------------------------------
# the registry + repo self-check
# ---------------------------------------------------------------------------

class TestRegistryAndSelfCheck:
    def test_every_entry_builds_and_lowers(self):
        avail = eps.available_entry_points()
        # the conftest forces 8 host devices: every entry must be here
        assert set(avail) == set(eps.ENTRY_POINTS)
        assert len(avail) >= 7

    def test_smoke_drivers_share_the_registry_builders(self):
        # the registry's GPT entry and the sanitizer smoke must build
        # through the same function object — one list of lowerable
        # steps, not parallel reconstructions
        import inspect

        from apex_tpu.analysis import sanitizer
        from apex_tpu.testing import standalone_gpt

        src = inspect.getsource(sanitizer.sanitize_smoke)
        assert "make_smoke_setup" in src and "build_train_step" in src
        src = inspect.getsource(standalone_gpt.train_smoke)
        assert "make_smoke_setup" in src and "build_train_step" in src
        src = inspect.getsource(eps._build_gpt_train_step)
        assert "make_smoke_setup" in src and "build_train_step" in src

    def test_repo_hlo_check_is_clean_and_baseline_current(self):
        """The acceptance bar: zero unsuppressed findings on every
        registered entry point against the COMMITTED baselines —
        i.e. the donation/promotion fixes shipped and the census/
        memory rows in tools/hlo_baseline.json are current."""
        unsuppressed, stale, audits = hlo.run_hlo_check(REPO)
        assert unsuppressed == [], "\n".join(
            f.render() for f in unsuppressed)
        assert stale == []
        assert len(audits) >= 7
        # the committed baseline has a row for every audited entry
        base = hlo.load_hlo_baseline(repo_root=REPO)
        assert set(audits) <= set(base["entries"])

    def test_multichip_census_covers_the_parallel_stack(self):
        base = hlo.load_hlo_baseline(repo_root=REPO)["entries"]
        assert "psum" in base["gpt_dp8_train_step"]["collectives"]
        zero = base["zero_dp8_update_step"]["collectives"]
        assert {"all_gather", "reduce_scatter"} <= set(zero)

    def test_train_steps_are_donated_end_to_end(self):
        """The APX601 payoff pinned down: params AND amp state (the
        masters + optimizer-state buffers) carry donation annotations
        in the lowered smoke train steps."""
        fn, args = eps.ENTRY_POINTS["gpt_train_step"].build()
        donated = hlo._donated_args(fn.lower(*args).as_text())
        n_leaves = sum(len(jax.tree_util.tree_leaves(a))
                       for a in args)
        assert len(donated) == n_leaves  # every input buffer donated

    def test_partial_update_preserves_unaudited_baseline_rows(
            self, tmp_path, monkeypatch):
        # --update-hlo-baseline with an --entry filter (or on a host
        # missing the multichip device count) must keep the committed
        # rows it did not re-measure — a partial update deleting 6 of
        # 7 entries would red the next full CI run
        import shutil

        (tmp_path / "tools").mkdir()
        shutil.copy(os.path.join(REPO, "tools", "hlo_baseline.json"),
                    tmp_path / "tools" / "hlo_baseline.json")
        audits = hlo.audit_entry_points(REPO,
                                        names=["gpt_train_step"])
        assert list(audits) == ["gpt_train_step"]
        hlo.write_hlo_baseline(audits, repo_root=str(tmp_path))
        after = hlo.load_hlo_baseline(repo_root=str(tmp_path))
        before = hlo.load_hlo_baseline(repo_root=REPO)
        assert set(after["entries"]) == set(before["entries"])
        assert after["entries"]["zero_dp8_update_step"] == \
            before["entries"]["zero_dp8_update_step"]

    def test_suppressions_for_unaudited_entries_not_stale(
            self, tmp_path):
        # a suppression belonging to a multichip entry must not be
        # reported stale by a filtered (or single-device) invocation
        # that never audited it; unattributable keys only go stale on
        # full runs
        import shutil

        (tmp_path / "tools").mkdir()
        shutil.copy(os.path.join(REPO, "tools", "hlo_baseline.json"),
                    tmp_path / "tools" / "hlo_baseline.json")
        (tmp_path / "tools" / "hlo_findings.txt").write_text(
            "<entry:gpt_dp8_train_step>:APX601:arg3  # hypothetical\n"
            "apex_tpu/x.py:APX602:gpt_dp8_train_step.f.bfloat16"
            "  # hypothetical\n"
            "orphan:APX900:nodots  # unattributable\n")
        _, stale, _ = hlo.run_hlo_check(str(tmp_path),
                                        names=["gpt_train_step"])
        assert stale == []
        # the full run still flags all three (entry audited + no
        # matching finding; orphan judged by full coverage)
        _, stale, audits = hlo.run_hlo_check(str(tmp_path))
        assert set(audits) == set(eps.ENTRY_POINTS)
        assert len(stale) == 3

    def test_cli_entry_typo_is_an_error(self):
        from apex_tpu.analysis.__main__ import main

        with pytest.raises(SystemExit) as e:
            main(["--check-hlo", "--entry", "gpt_tran_step"])
        assert e.value.code == 2  # argparse error, not "hlo clean"

    def test_stale_baseline_entry_fails(self, tmp_path):
        base = hlo.load_hlo_baseline(repo_root=REPO)
        base["entries"]["ghost_entry"] = {"collectives": {},
                                          "peak_live_bytes": 1}
        (tmp_path / "tools").mkdir()
        (tmp_path / "tools" / "hlo_baseline.json").write_text(
            json.dumps(base))
        (tmp_path / "tools" / "hlo_findings.txt").write_text("")
        # lower only the cheapest entry; the stale row still fails
        unsuppressed, _, _ = hlo.run_hlo_check(
            str(tmp_path), names=["fixture_none"])
        stale = [f for f in unsuppressed
                 if f.symbol == "stale-entry"]
        assert len(stale) == 1 and "ghost_entry" in stale[0].message


# ---------------------------------------------------------------------------
# rule registry + CLI surface
# ---------------------------------------------------------------------------

class TestRulesRegistry:
    def test_apx6xx_rules_registered(self):
        from apex_tpu.analysis.rules import RULES

        for rid in ("APX601", "APX602", "APX603", "APX604", "APX605"):
            assert rid in RULES
            assert RULES[rid].layer == "compiled"

    def test_rule_table_covers_linter_and_hlo(self):
        from apex_tpu.analysis.rules import render_rule_table

        table = render_rule_table()
        for rid in ("APX101", "APX301", "APX401", "APX501", "APX601",
                    "APX605", "APX900"):
            assert f"`{rid}`" in table

    def test_duplicate_rule_rejected(self):
        from apex_tpu.analysis.rules import register_rule

        with pytest.raises(ValueError, match="duplicate"):
            register_rule("APX601", "compiled", "x", "y")

    def test_entrypoint_fields_are_frozen_data(self):
        ep = eps.ENTRY_POINTS["gpt_train_step"]
        assert dataclasses.is_dataclass(ep)
        with pytest.raises(dataclasses.FrozenInstanceError):
            ep.policy = "O0"


# ---------------------------------------------------------------------------
# ISSUE-10 regression fixture: the 8-device ZeRO entries under the HLO
# auditor — donation-clean, and the census rows cover BOTH halves of
# the ZeRO exchange (psum_scatter -> reduce_scatter, all_gather) with
# exact provenance, so a refactor that drops either collective (or
# un-donates the state) fails here before it fails on a pod.
# ---------------------------------------------------------------------------

class TestZeroEntriesRegression:
    ZERO_ENTRIES = ("zero_dp8_update_step", "zero_dp8_adam_step")

    @pytest.fixture(scope="class")
    def audits(self):
        return hlo.audit_entry_points(REPO, names=list(
            self.ZERO_ENTRIES))

    def test_donation_clean(self, audits):
        for name in self.ZERO_ENTRIES:
            missed = [f for f in audits[name].findings
                      if f.rule == "APX601"]
            assert missed == [], "\n".join(
                f.render() for f in missed)

    def test_census_covers_scatter_and_gather_with_provenance(
            self, audits):
        for name in self.ZERO_ENTRIES:
            kinds = {op.kind for op in audits[name].collectives}
            assert {"reduce_scatter", "all_gather"} <= kinds, name
        # exact provenance: the update entry's pair lives in its own
        # shard fn; the adam entry's grad scatter + delta gather live
        # in the OPTIMIZER (distributed_fused_adam.update), with the
        # extra rank-derivation scatter priced to the compat shim
        upd = audits["zero_dp8_update_step"].collectives
        assert all(op.path == "apex_tpu/testing/entry_points.py"
                   and op.function == "shard" for op in upd)
        adam = audits["zero_dp8_adam_step"].collectives
        opt = "apex_tpu/contrib/optimizers/distributed_fused_adam.py"
        assert any(op.kind == "reduce_scatter" and op.path == opt
                   and op.function == "update" for op in adam)
        assert all(op.path == opt for op in adam
                   if op.kind == "all_gather")

    def test_committed_baseline_rows_price_both_kinds(self):
        base = hlo.load_hlo_baseline(repo_root=REPO)["entries"]
        for name in self.ZERO_ENTRIES:
            cens = base[name]["collectives"]
            assert {"reduce_scatter", "all_gather"} <= set(cens), name
            for kind in ("reduce_scatter", "all_gather"):
                assert cens[kind]["count"] >= 1
                assert cens[kind]["bytes_per_step"] > 0
        # the adam entry donates params AND every state leaf (the
        # end-to-end requirement: a missed state donation doubles the
        # largest buffers in the step)
        adam = base["zero_dp8_adam_step"]
        n_state_leaves = 3  # count + m[0] + v[0]
        assert len(adam["donated_args"]) >= 2 + n_state_leaves - 1
