"""Orbax-backed sharded checkpointing: amp-aware round trip, resharded
restore, async manager semantics (TPU-native upgrade of the reference's
state-dict flow, ref: apex/amp/frontend.py:428-454 + imagenet --resume)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.utils import CheckpointManager, load_checkpoint, save_checkpoint


def _toy_params(key=0):
    k = jax.random.PRNGKey(key)
    return {"w": jax.random.normal(k, (16, 8), jnp.float32),
            "b": jnp.zeros((8,), jnp.float32)}


class TestAmpRoundTrip:
    def test_masters_and_scaler_survive(self, tmp_path):
        params0 = _toy_params()
        cast, opt, state = amp.initialize(params0, optax.sgd(0.1),
                                          opt_level="O2")
        # advance: one skipped (inf) + one real step so scaler state and
        # masters are both non-trivial
        inf = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, jnp.inf), cast)
        cast, state, _ = opt.apply_gradients(inf, state, cast)
        g = jax.tree_util.tree_map(jnp.ones_like, cast)
        cast, state, _ = opt.apply_gradients(g, state, cast)

        save_checkpoint(str(tmp_path / "ck"), 7, cast, opt, state)

        # fresh state, then restore
        cast2, opt2, state2 = amp.initialize(params0, optax.sgd(0.1),
                                             opt_level="O2")
        cast2, state2, _, step = load_checkpoint(
            str(tmp_path / "ck"), cast2, opt2, state2)
        assert step == 7
        assert float(state2.scaler.loss_scale) == \
            float(state.scaler.loss_scale)
        assert int(state2.scaler.steps_skipped) == 1
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            state.master_params, state2.master_params)
        # model params re-cast from masters, model dtype preserved
        assert cast2["w"].dtype == cast["w"].dtype
        np.testing.assert_array_equal(np.asarray(cast2["w"]),
                                      np.asarray(cast["w"]))

    def test_plain_params_no_amp(self, tmp_path):
        params = _toy_params(3)
        save_checkpoint(str(tmp_path / "ck2"), 1, params)
        restored, _, _, step = load_checkpoint(str(tmp_path / "ck2"),
                                               _toy_params(4))
        assert step == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(params["w"]))


class TestReshardedRestore:
    def test_save_sharded_restore_other_sharding(self, tmp_path):
        devs = jax.devices()[:8]
        mesh_a = Mesh(np.array(devs).reshape(8), ("data",))
        mesh_b = Mesh(np.array(devs).reshape(4, 2), ("x", "y"))
        x = jnp.arange(64 * 8, dtype=jnp.float32).reshape(64, 8)
        xa = jax.device_put(x, NamedSharding(mesh_a, P("data", None)))
        save_checkpoint(str(tmp_path / "ck3"), 2, {"x": xa})
        # template on a DIFFERENT mesh/sharding
        tmpl = {"x": jax.device_put(
            jnp.zeros_like(x), NamedSharding(mesh_b, P("y", "x")))}
        restored, _, _, _ = load_checkpoint(str(tmp_path / "ck3"), tmpl)
        assert restored["x"].sharding.spec == P("y", "x")
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(x))


class TestManager:
    def test_async_save_keep_and_extra(self, tmp_path):
        with CheckpointManager(str(tmp_path / "mgr"), keep=2) as mgr:
            p = _toy_params(5)
            for s in (1, 2, 3):
                mgr.save(s, p, extra={"cursor": jnp.int32(s * 10)})
            mgr.wait()
            assert mgr.latest_step() == 3
            _, _, extra, step = mgr.restore(
                p, extra={"cursor": jnp.int32(0)})
            assert step == 3 and int(extra["cursor"]) == 30
            # keep=2: step 1 garbage-collected
            _, _, _, s2 = mgr.restore(p, step=2)
            assert s2 == 2
            with pytest.raises(Exception):
                mgr.restore(p, step=1)

    def test_restore_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_checkpoint(str(tmp_path / "nope"), _toy_params())

    def test_restore_missing_explicit_step_lists_available(self,
                                                           tmp_path):
        # a clear FileNotFoundError naming dir + steps, not a raw
        # Orbax traceback (see also tests/test_resilience.py)
        with CheckpointManager(str(tmp_path / "mgr2")) as mgr:
            p = _toy_params(6)
            mgr.save(5, p)
            mgr.wait()
            with pytest.raises(FileNotFoundError) as ei:
                mgr.restore(p, step=9)
        assert "step 9" in str(ei.value) and "[5]" in str(ei.value)


class TestIntegrityFallbackAmp:
    def test_corrupt_latest_falls_back_with_amp_state(self, tmp_path):
        """The integrity fallback composes with the amp layout: a torn
        newest step is skipped and the previous step's masters + scaler
        state restore intact."""
        from apex_tpu.resilience import corrupt_checkpoint

        params0 = _toy_params()
        cast, opt, state = amp.initialize(params0, optax.sgd(0.1),
                                          opt_level="O2")
        d = str(tmp_path / "ckamp")
        snapshots = {}
        with CheckpointManager(d, keep=5) as mgr:
            for s in (1, 2):
                g = jax.tree_util.tree_map(jnp.ones_like, cast)
                cast, state, _ = opt.apply_gradients(g, state, cast)
                snapshots[s] = state
                mgr.save(s, cast, opt, state)
            mgr.wait()
        corrupt_checkpoint(d, step=2, mode="truncate")

        cast2, opt2, state2 = amp.initialize(params0, optax.sgd(0.1),
                                             opt_level="O2")
        cast2, state2, _, step = load_checkpoint(d, cast2, opt2, state2)
        assert step == 1
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            snapshots[1].master_params, state2.master_params)
        assert float(state2.scaler.loss_scale) == \
            float(snapshots[1].scaler.loss_scale)
