"""Live metrics plane tests (ISSUE-17): the exposition golden for
counter/gauge/histogram rendering, the lock-free exporter publish /
staleness semantics, the MetricsServer's three endpoints live over
HTTP (including the healthz 503 flip and a scrape racing the serve),
the FleetAggregator's measured-tick rate math and trend rings, and
the JSONL -> exporter reconstruction property proving the event log
stays the complete source of truth.
"""
import json
import urllib.error
import urllib.request

import pytest

import jax
import jax.numpy as jnp

from apex_tpu.monitor import Event, MemorySink, load_events
from apex_tpu.monitor.export import (FleetAggregator, MetricsExporter,
                                     MetricsRegistry, MetricsServer,
                                     registry_from_serve_events)
from apex_tpu.serving import (BucketLadder, Request, ServingEngine,
                              ServingModelConfig,
                              default_cache_config,
                              extract_serving_weights)
from apex_tpu.testing.standalone_gpt import GPTModel


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

class StubMonitor:
    def __init__(self):
        self.sink = MemorySink()
        self.watchdog = None

    def event(self, kind, name, value=None, step=None, **attrs):
        self.sink.emit(Event(time=float(step or 0), step=step,
                             kind=kind, name=name, value=value,
                             attrs=attrs))


def _tiny_model(vocab=32, hidden=16, heads=2, layers=2, max_seq=32):
    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_sequence_length=max_seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    params = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(monitor=None, exporter=None, *, ladder=None,
            num_blocks=16, block_size=4, slo=None):
    model, params = _tiny_model()
    cfg = ServingModelConfig.from_model(
        model, prefill_flash=False, decode_attention="reference")
    weights = extract_serving_weights(params, cfg.num_layers)
    cache_cfg = default_cache_config(cfg, num_blocks=num_blocks,
                                     block_size=block_size)
    return ServingEngine(weights, cfg, cache_cfg,
                         ladder=ladder or BucketLadder(batch=(2, 4),
                                                       pages=(3,)),
                         monitor=monitor, exporter=exporter, slo=slo)


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.getcode(), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# registry + exposition format
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_gauge_golden_exposition(self):
        reg = MetricsRegistry()
        c = reg.counter("apex_tpu_requests_total", "Requests seen.")
        c.inc(2.0, terminal="finished")
        c.inc(1.0, terminal="shed")
        g = reg.gauge("apex_tpu_queue_depth", "Queue depth.")
        g.set(3.0)
        # families sort by name, labels sort within a family, and
        # integral floats print as integers — the golden every
        # scraper-compat claim rests on
        assert reg.render() == (
            "# HELP apex_tpu_queue_depth Queue depth.\n"
            "# TYPE apex_tpu_queue_depth gauge\n"
            "apex_tpu_queue_depth 3\n"
            "# HELP apex_tpu_requests_total Requests seen.\n"
            "# TYPE apex_tpu_requests_total counter\n"
            'apex_tpu_requests_total{terminal="finished"} 2\n'
            'apex_tpu_requests_total{terminal="shed"} 1\n')

    def test_label_escaping_and_float_values(self):
        reg = MetricsRegistry()
        g = reg.gauge("apex_tpu_g", "h")
        g.set(1.5, reason='a"b\\c\nd')
        out = reg.render()
        assert 'reason="a\\"b\\\\c\\nd"' in out
        assert out.rstrip().endswith("1.5")

    def test_registration_idempotent_kind_mismatch_raises(self):
        reg = MetricsRegistry()
        a = reg.counter("apex_tpu_x_total", "h")
        assert reg.counter("apex_tpu_x_total", "h") is a
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("apex_tpu_x_total", "h")

    def test_histogram_cumulative_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("apex_tpu_lat_ms", "Latency.",
                          buckets=(1.0, 5.0, 10.0))
        for v in (0.5, 0.7, 3.0, 7.0, 100.0):
            h.observe(v)
        lines = reg.render().splitlines()
        samples = [ln for ln in lines if not ln.startswith("#")]
        # le buckets are CUMULATIVE and +Inf equals _count
        assert samples == [
            'apex_tpu_lat_ms_bucket{le="1"} 2',
            'apex_tpu_lat_ms_bucket{le="5"} 3',
            'apex_tpu_lat_ms_bucket{le="10"} 4',
            'apex_tpu_lat_ms_bucket{le="+Inf"} 5',
            "apex_tpu_lat_ms_sum 111.2",
            "apex_tpu_lat_ms_count 5",
        ]
        # samples() collapses to the observation count (the shape the
        # reconstruction property diffs)
        assert h.samples() == {(): 5.0}


# ---------------------------------------------------------------------------
# exporter publish / staleness
# ---------------------------------------------------------------------------

class TestMetricsExporter:
    def test_publish_swaps_state_and_stamps_staleness(self):
        t = [100.0]
        exp = MetricsExporter(wall_clock=lambda: t[0])
        # before the first publish: healthy "starting", empty varz,
        # a render that still carries the meta families
        ok, payload = exp.healthz()
        assert ok and payload["status"] == "starting"
        assert exp.varz() == {}
        assert "apex_tpu_exporter_publishes_total 0" in exp.render()
        reg = MetricsRegistry()
        reg.gauge("apex_tpu_g", "h").set(7)
        exp.publish(reg, tick=3, health={"ok": True, "status": "ok"},
                    varz={"tick": 3})
        t[0] = 102.5
        out = exp.render()
        assert "apex_tpu_g 7" in out
        assert "apex_tpu_exporter_publishes_total 1" in out
        assert "apex_tpu_exporter_staleness_seconds 2.5" in out
        ok, payload = exp.healthz()
        assert ok and payload["staleness_s"] == pytest.approx(2.5)
        assert payload["tick"] == 3
        assert exp.varz() == {"tick": 3}

    def test_unhealthy_publish_flips_healthz(self):
        exp = MetricsExporter(wall_clock=lambda: 0.0)
        exp.publish(MetricsRegistry(), tick=9,
                    health={"ok": False, "status": "draining",
                            "draining": True})
        ok, payload = exp.healthz()
        assert not ok
        assert payload["status"] == "draining" and payload["draining"]

    def test_scrape_reads_frozen_reference(self):
        # the lock-free contract: a scrape renders from the reference
        # it loaded; a publish AFTER the load must not tear it
        exp = MetricsExporter(wall_clock=lambda: 0.0)
        reg = MetricsRegistry()
        reg.gauge("apex_tpu_g", "h").set(1)
        exp.publish(reg, tick=1)
        st = exp.state
        reg2 = MetricsRegistry()
        reg2.gauge("apex_tpu_g", "h").set(2)
        exp.publish(reg2, tick=2)
        assert "apex_tpu_g 1" in st.text          # frozen snapshot
        assert "apex_tpu_g 2" in exp.state.text   # the new reference


# ---------------------------------------------------------------------------
# HTTP server (live endpoints)
# ---------------------------------------------------------------------------

class TestMetricsServer:
    def test_endpoints_live_and_lifecycle_events_pair(self):
        mon = StubMonitor()
        exp = MetricsExporter()
        reg = MetricsRegistry()
        reg.gauge("apex_tpu_serve_queue_depth", "h").set(4)
        exp.publish(reg, tick=2,
                    health={"ok": True, "status": "ok"},
                    varz={"tick": 2, "active": 1})
        srv = MetricsServer(exp, port=0, monitor=mon)
        try:
            port = srv.start()
            assert port > 0 and srv.port == port
            code, body = _get(srv.url("/metrics"))
            assert code == 200
            assert "apex_tpu_serve_queue_depth 4" in body
            assert "apex_tpu_exporter_staleness_seconds" in body
            code, body = _get(srv.url("/healthz"))
            assert code == 200
            assert json.loads(body)["status"] == "ok"
            code, body = _get(srv.url("/varz"))
            assert code == 200 and json.loads(body)["active"] == 1
            code, _ = _get(srv.url("/nope"))
            assert code == 404
            # an unhealthy publish flips /healthz to 503 on the very
            # next scrape — no handler restart involved
            exp.publish(reg, tick=3,
                        health={"ok": False, "status": "draining",
                                "draining": True})
            code, body = _get(srv.url("/healthz"))
            assert code == 503
            assert json.loads(body)["draining"] is True
        finally:
            srv.stop()
        # the port is closed after stop
        with pytest.raises(OSError):
            urllib.request.urlopen(srv.url("/healthz"), timeout=0.5)
        names = [e.name for e in mon.sink.by_kind("metrics")]
        assert names == ["metrics_server_started",
                         "metrics_server_stopped"]
        started = mon.sink.by_name("metrics_server_started")[0]
        assert started.attrs["port"] == port

    def test_scrape_races_the_serve(self):
        # a scrape mid-run sees a consistent, recent snapshot — the
        # lock-free swap means the handler can never block the tick
        mon = StubMonitor()
        exp = MetricsExporter()
        eng = _engine(monitor=mon, exporter=exp)
        srv = MetricsServer(exp, port=0, monitor=mon)
        srv.start()
        seen = []

        def scrape(tick):
            if tick == 1:
                code, body = _get(srv.url("/metrics"))
                hcode, hbody = _get(srv.url("/healthz"))
                seen.append((code, body, hcode, hbody))
        try:
            for i in range(3):
                eng.submit(Request(rid=f"r{i}", prompt=[3 + i, 7],
                                   max_new_tokens=4))
            eng.run(after_tick=scrape)
        finally:
            srv.stop()
        assert len(seen) == 1
        code, body, hcode, hbody = seen[0]
        assert code == 200 and hcode == 200
        assert "apex_tpu_serve_tick " in body
        payload = json.loads(hbody)
        assert payload["status"] == "ok"
        assert payload["staleness_s"] < 60.0


# ---------------------------------------------------------------------------
# fleet aggregation + trends
# ---------------------------------------------------------------------------

class TestFleetAggregator:
    def _snap(self, tick, tokens, queue=2, avail=10, reserved=3,
              active=1, prefilling=0, compiles=1):
        return {"tick": tick, "tokens_generated": tokens,
                "queue_depth": queue, "available_blocks": avail,
                "reserved_blocks": reserved, "active": active,
                "prefilling": prefilling, "compiles": compiles}

    def test_rates_use_measured_tick_deltas(self):
        agg = FleetAggregator(window=8)
        # first observe: no previous marks, so every delta is 0
        a0 = agg.observe(0, {"r0": self._snap(10, 100),
                             "r1": self._snap(10, 100)})
        assert a0["ticks"] == 0 and a0["new_tokens"] == 0
        # r0 advanced 4 engine ticks, r1 only 2 (a swap-drain gap):
        # the denominator is the MEASURED sum, never rounds * nominal
        a1 = agg.observe(1, {"r0": self._snap(14, 120),
                             "r1": self._snap(12, 106)})
        assert a1["ticks"] == 6
        assert a1["new_tokens"] == 26
        assert a1["replicas"] == 2
        assert a1["queue_depth"] == 4
        # free blocks are NET of reservations: 2 * (10 - 3)
        assert a1["free_blocks_net"] == 14
        # backlog = queued + prefilling + active across the fleet
        assert a1["backlog"] == 2 * (2 + 1)
        assert a1["ewma_tokens_per_tick"] > 0

    def test_replica_reset_never_goes_negative(self):
        agg = FleetAggregator()
        agg.observe(0, {"r0": self._snap(50, 500)})
        # a rolling weight swap restarted r0: cumulative counters
        # reset below the marks — the delta clamps to 0, not -500
        a = agg.observe(1, {"r0": self._snap(2, 10)})
        assert a["new_tokens"] == 0 and a["ticks"] == 0

    def test_trend_slope_and_ring_bound(self):
        agg = FleetAggregator(window=4)
        for t in range(10):
            agg.observe(t, {"r0": self._snap(t + 1, 0,
                                             queue=2 * t)})
        trends = agg.trends()
        assert set(trends) == set(FleetAggregator.SERIES)
        qd = trends["queue_depth"]
        # queue depth grows by 2/round; the bounded ring holds the
        # last 4 points and the least-squares slope reads the growth
        assert qd["n"] == 4
        assert qd["slope"] == pytest.approx(2.0)
        assert agg.observations == 10


# ---------------------------------------------------------------------------
# JSONL -> exporter reconstruction (source-of-truth property)
# ---------------------------------------------------------------------------

class TestReconstructionProperty:
    # the families registry_from_serve_events rebuilds; the live
    # export_registry must agree sample-for-sample on every one
    SHARED = ("apex_tpu_serve_requests_total",
              "apex_tpu_serve_tokens_total",
              "apex_tpu_serve_rejected_total",
              "apex_tpu_serve_queue_depth",
              "apex_tpu_serve_free_blocks",
              "apex_tpu_serve_pool_blocks",
              "apex_tpu_serve_tick",
              "apex_tpu_serve_compiles_total")

    def test_rebuilt_registry_matches_live_export(self, tmp_path):
        mon = StubMonitor()
        eng = _engine(monitor=mon)
        for i in range(3):
            eng.submit(Request(rid=f"r{i}", prompt=[3 + i, 7, 5],
                               max_new_tokens=3))
        with pytest.raises(ValueError):
            eng.submit(Request(rid="bad", prompt=[1],
                               max_new_tokens=0))
        eng.run()
        live = eng.export_registry().samples()
        rebuilt = registry_from_serve_events(
            list(mon.sink.events)).samples()
        for fam in self.SHARED:
            assert rebuilt.get(fam) == live.get(fam), fam

    def test_property_survives_the_jsonl_round_trip(self, tmp_path):
        # same property through an actual file: serialize, load_events,
        # rebuild — proving the on-disk log is sufficient
        from apex_tpu.monitor import JsonlSink

        jsonl = tmp_path / "serve.jsonl"
        sink = JsonlSink(str(jsonl))
        mon = StubMonitor()
        mon.sink = sink
        eng = _engine(monitor=mon)
        for i in range(2):
            eng.submit(Request(rid=f"r{i}", prompt=[2, 4 + i],
                               max_new_tokens=3))
        eng.run()
        sink.close()
        events, malformed = load_events(str(jsonl))
        assert malformed == 0
        rebuilt = registry_from_serve_events(events).samples()
        live = eng.export_registry().samples()
        for fam in self.SHARED:
            assert rebuilt.get(fam) == live.get(fam), fam
        # and the rebuilt registry renders as a valid document
        text = registry_from_serve_events(events).render()
        assert "# TYPE apex_tpu_serve_requests_total counter" in text
