"""SPMD sharding auditor (apex_tpu.analysis.sharding) + MeshPlan.

Per-rule synthetic fixtures — one per APX701-705, each proving the
rule FIRES with exact rule id + provenance — plus the acceptance bar:
``run_sharding_check`` green on every planned multichip entry against
the committed ``tools/sharding_baseline.json``, and the
deliberately-reintroduced ZeRO replicated-state bug (the real finding
this PR fixed in bench.py) caught as APX701.
"""
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu._compat import shard_map
from apex_tpu.analysis import sharding
from apex_tpu.mesh_plan import MeshAxis, MeshPlan
from apex_tpu.testing import entry_points as eps

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


def _plan8(**kw):
    return MeshPlan.build(axes=(("zero", 8, "zero"),), **kw)


def _mesh8():
    return _plan8().make_mesh()


# ---------------------------------------------------------------------------
# MeshPlan: the frozen topology contract
# ---------------------------------------------------------------------------

class TestMeshPlan:
    def test_build_and_queries(self):
        plan = MeshPlan.build(
            axes=(("pipe", 2, "pipeline"), ("data", 2, "data"),
                  ("tensor", 2, "tensor")),
            tensor_specs={r"^in0$": ("data", None, "tensor")},
            collective_budget={"psum": 3})
        assert plan.world_size == 8
        assert plan.axis("data").kind == "data"
        assert plan.axes_of_kind("tensor") == (MeshAxis("tensor", 2,
                                                        "tensor"),)
        assert plan.budget() == {"psum": 3}
        assert plan.describe() == \
            "pipe=2(pipeline) x data=2(data) x tensor=2(tensor)"

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown parallelism"):
            MeshPlan.build(axes=(("x", 2, "banana"),))
        with pytest.raises(ValueError, match="duplicate axis"):
            MeshPlan.build(axes=(("x", 2, "data"), ("x", 2, "data")))
        with pytest.raises(ValueError, match="names axis"):
            MeshPlan.build(axes=(("x", 2, "data"),),
                           tensor_specs={"a": ("y",)})

    def test_spec_for_first_match_wins_and_with_specs_prepends(self):
        plan = _plan8(tensor_specs={r"\.m\b": ("zero",), r".": ()})
        assert plan.spec_for("state.m[0]") == ("zero",)
        assert plan.spec_for("state.count") == ()
        special = plan.with_specs({r"state\.m\[0\]": ()})
        assert special.spec_for("state.m[0]") == ()
        assert special.spec_for("state.m[1]") == ("zero",)

    def test_expected_shard_shape_and_divisibility(self):
        plan = _plan8()
        assert plan.expected_shard_shape((64, 16), ("zero",)) == (8, 16)
        assert plan.expected_shard_shape((64, 16), ()) == (64, 16)
        with pytest.raises(ValueError, match="not divisible"):
            plan.expected_shard_shape((63,), ("zero",))
        with pytest.raises(ValueError, match="more dims"):
            plan.expected_shard_shape((8,), ("zero", None))

    def test_json_roundtrip(self):
        plan = MeshPlan.build(
            axes=(("tensor", 2, "tensor"), ("expert", 4, "expert")),
            tensor_specs={r"\['wi'\]": (("tensor", "expert"),),
                          r"\['b'\]": (None, "expert")},
            collective_budget={"all_to_all": 4})
        again = MeshPlan.from_json(
            json.loads(json.dumps(plan.to_json())))
        assert again == plan

    def test_json_roundtrip_preserves_shadowing_override(self):
        """with_specs PREPENDS; a dict-keyed serialization would keep
        the LOSING base spec for a shadowed pattern — the pair-list
        form must round-trip the winner."""
        plan = _plan8(tensor_specs={r"x": ("zero",)}).with_specs(
            {r"x": ()})
        assert plan.spec_for("x") == ()
        again = MeshPlan.from_json(
            json.loads(json.dumps(plan.to_json())))
        assert again == plan
        assert again.spec_for("x") == ()

    def test_partition_spec_and_make_mesh(self):
        plan = _plan8(tensor_specs={r"\.m\b": ("zero",)})
        assert plan.partition_spec("s.m[0]") == P("zero")
        assert plan.partition_spec("undeclared") == P()
        mesh = plan.make_mesh()
        assert mesh.axis_names == ("zero",)
        assert mesh.devices.shape == (8,)

    def test_tensor_paths_naming(self):
        tree = {"a": jnp.zeros((2,)), "b": [jnp.zeros(()),
                                            jnp.zeros((3,))]}
        paths = sharding.tensor_paths(tree, "in0")
        assert paths == ["in0['a']", "in0['b'][0]", "in0['b'][1]"]


# ---------------------------------------------------------------------------
# per-rule synthetic fixtures
# ---------------------------------------------------------------------------

class TestRuleFixtures:
    def test_apx701_replicated_where_plan_shards(self):
        """A 4 KiB tensor the plan shards over 'zero' propagated fully
        replicated: the silent-ZeRO-regression fixture."""
        mesh = _mesh8()
        plan = _plan8(tensor_specs={r"^in0\.m\b": ("zero",)})
        aval = jax.core.ShapedArray((1024,), jnp.float32)
        out = sharding._spec_findings(
            "fx", plan, ["in0.m[0]"], [NamedSharding(mesh, P())],
            [aval], None)
        assert [f.rule for f in out] == ["APX701"]
        assert "fully REPLICATED" in out[0].message
        assert "in0.m[0]" in out[0].message
        assert "(128,)" in out[0].message  # the promised shard shape

    def test_apx701_floor_exempts_scalars(self):
        mesh = _mesh8()
        plan = _plan8(tensor_specs={r"^in0$": ("zero",)})
        aval = jax.core.ShapedArray((8,), jnp.float32)  # 32 bytes
        out = sharding._spec_findings(
            "fx", plan, ["in0"], [NamedSharding(mesh, P())], [aval],
            None)
        assert out == []

    def test_apx703_drift_stale_pattern_and_budget(self):
        mesh = _mesh8()
        # drift: plan says replicated, partitioner sharded it
        plan = _plan8(tensor_specs={r"^in0$": ()})
        aval = jax.core.ShapedArray((64, 4), jnp.float32)
        out = sharding._spec_findings(
            "fx", plan, ["in0"], [NamedSharding(mesh, P("zero"))],
            [aval], None)
        assert [f.rule for f in out] == ["APX703"]
        assert "partitioner assigned" in out[0].message
        # stale pattern: a declared spec matching no audited tensor
        plan2 = _plan8(tensor_specs={r"ghost": ("zero",)})
        out2 = sharding._spec_findings("fx", plan2, ["in0"],
                                       [NamedSharding(mesh, P())],
                                       [aval], None)
        assert [f.rule for f in out2] == ["APX703"]
        assert "matches no audited tensor" in out2[0].message
        # budget: unbudgeted kind + overrun, with op provenance
        def prog(x):
            return shard_map(
                lambda x: jax.lax.psum(
                    jax.lax.psum(x, "zero"), "zero"),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)(x)

        jaxpr = jax.make_jaxpr(prog)(jnp.ones((8,)))
        census, ops = sharding._collective_census(jaxpr.jaxpr)
        assert census == {"psum": 2}
        plan3 = _plan8(collective_budget={"psum": 1})
        out3 = sharding._budget_findings("fx", plan3, census, ops,
                                         REPO)
        assert [f.rule for f in out3] == ["APX703"]
        assert "exceeds the plan budget: 2" in out3[0].message
        assert "test_analysis_sharding.py" in out3[0].message
        plan4 = _plan8(collective_budget={"all_gather": 1})
        out4 = sharding._budget_findings("fx", plan4, census, ops,
                                         REPO)
        # unbudgeted psum fires; the budgeted-but-unseen all_gather
        # does NOT (the budget is a ceiling, not an exact count)
        assert [f.rule for f in out4] == ["APX703"]
        assert "UNBUDGETED" in out4[0].message

    def test_apx702_gather_then_rescatter_chain(self):
        """all_gather feeding a reduce_scatter of the same operand —
        through a dtype convert — is the wasted-bytes chain."""
        mesh = _mesh8()

        def prog(x):
            def f(x):
                g = jax.lax.all_gather(x, "zero", axis=0, tiled=True)
                g16 = g.astype(jnp.bfloat16)  # pass-through hop
                return jax.lax.psum_scatter(
                    g16.astype(jnp.float32), "zero",
                    scatter_dimension=0, tiled=True)

            return shard_map(f, mesh=mesh, in_specs=P("zero"),
                             out_specs=P("zero"), check_vma=False)(x)

        jaxpr = jax.make_jaxpr(prog)(jnp.ones((64,)))
        errors, _ = sharding._chain_findings("fx", jaxpr.jaxpr, REPO)
        assert [f.rule for f in errors] == ["APX702"]
        msg = errors[0].message
        assert "all_gather" in msg and "reduce_scatter" in msg
        assert "test_analysis_sharding.py" in msg  # both provenances

    def test_apx702_clean_gather_no_finding(self):
        mesh = _mesh8()

        def prog(x):
            return shard_map(
                lambda x: jax.lax.all_gather(x, "zero", axis=0,
                                             tiled=True) * 2.0,
                mesh=mesh, in_specs=P("zero"), out_specs=P(),
                check_vma=False)(x)

        jaxpr = jax.make_jaxpr(prog)(jnp.ones((64,)))
        errors, _ = sharding._chain_findings("fx", jaxpr.jaxpr, REPO)
        assert errors == []

    def test_apx704_non_overlappable_collective(self):
        """The collective's output consumed by the NEXT equation while
        independent compute exists later -> advisory; hoisting the
        independent compute between them -> silence."""
        mesh = _mesh8()

        def tight(x, a):
            def f(x, a):
                g = jax.lax.all_to_all(x, "zero", 0, 0)
                y = g * 2.0             # zero slack after the a2a
                w = a @ a               # independent, could overlap
                return y.sum() + w.sum()

            return shard_map(f, mesh=mesh, in_specs=(P("zero"), P()),
                             out_specs=P(), check_vma=False)(x, a)

        x = jnp.ones((64, 8))  # local (8, 8): a2a splits dim 0 by 8
        a = jnp.ones((4, 4))
        jaxpr = jax.make_jaxpr(tight)(x, a)
        _, advisories = sharding._chain_findings("fx", jaxpr.jaxpr,
                                                 REPO)
        assert [f.rule for f in advisories] == ["APX704"]
        assert "all_to_all" in advisories[0].message
        assert advisories[0].severity == "advisory"

        def hoisted(x, a):
            def f(x, a):
                g = jax.lax.all_to_all(x, "zero", 0, 0)
                w = a @ a               # slack: a2a can overlap this
                y = g * 2.0
                return y.sum() + w.sum()

            return shard_map(f, mesh=mesh, in_specs=(P("zero"), P()),
                             out_specs=P(), check_vma=False)(x, a)

        jaxpr2 = jax.make_jaxpr(hoisted)(x, a)
        _, adv2 = sharding._chain_findings("fx", jaxpr2.jaxpr, REPO)
        assert adv2 == []

    def test_apx704_moe_overlapped_exchange_goes_quiet(self):
        """ISSUE-19 regression: the chunked expert exchange issues
        the dispatch a2a's back-to-back and trails each return a2a
        with the NEXT chunk's expert matmul, so the overlap advisory
        is silent; ``a2a_chunks=1`` restores the legacy single-shot
        trace — expert matmul consuming the dispatch a2a immediately
        — and with it the advisory."""
        from apex_tpu.transformer.expert_parallel import (
            moe_dispatch_combine_fused)

        mesh = _mesh8()
        e, h = 8, 16
        x = jnp.ones((256, h))
        logits = jnp.ones((256, e))
        w = jnp.ones((e, h, h))

        def prog(chunks):
            def f(x, logits, w):
                y, _ = moe_dispatch_combine_fused(
                    x, logits,
                    lambda d: jnp.einsum(
                        "ech,ehf->ecf", d, w,
                        preferred_element_type=jnp.float32),
                    e, capacity_factor=4.0, axis_name="zero",
                    a2a_chunks=chunks)
                return y

            return shard_map(
                f, mesh=mesh,
                in_specs=(P("zero"), P("zero"), P("zero")),
                out_specs=P("zero"), check_vma=False)

        jaxpr = jax.make_jaxpr(prog(2))(x, logits, w)
        _, adv = sharding._chain_findings("fx", jaxpr.jaxpr, REPO)
        assert [f.rule for f in adv] == []

        jaxpr1 = jax.make_jaxpr(prog(1))(x, logits, w)
        _, adv1 = sharding._chain_findings("fx", jaxpr1.jaxpr, REPO)
        assert any(f.rule == "APX704" and "all_to_all" in f.message
                   for f in adv1)

    def test_apx705_memory_gate_and_plan_drift(self):
        plan_json = _plan8().to_json()
        audit = sharding.ShardingAudit(
            name="fx", plan_json=plan_json, per_device_bytes=1000,
            census={}, findings=[], advisories=[])
        row = audit.baseline_row()
        # within +/-10%: silent
        assert sharding._baseline_findings(
            "fx", audit, dict(row, per_device_bytes=950)) == []
        grew = sharding._baseline_findings(
            "fx", audit, dict(row, per_device_bytes=800))
        assert [f.rule for f in grew] == ["APX705"]
        assert "grew >10%" in grew[0].message
        shrank = sharding._baseline_findings(
            "fx", audit, dict(row, per_device_bytes=1200))
        assert [f.rule for f in shrank] == ["APX705"]
        assert "shrank >10%" in shrank[0].message
        missing = sharding._baseline_findings("fx", audit, None)
        assert [f.rule for f in missing] == ["APX705"]
        assert "no committed sharding-baseline row" in \
            missing[0].message
        other = dict(row)
        other["plan"] = _plan8(
            collective_budget={"psum": 1}).to_json()
        drift = sharding._baseline_findings("fx", audit, other)
        assert [f.rule for f in drift] == ["APX703"]
        assert "MeshPlan changed" in drift[0].message


# ---------------------------------------------------------------------------
# the real bug, reintroduced: replicated ZeRO state -> APX701
# ---------------------------------------------------------------------------

class TestZeroRegressionCaught:
    def test_replicated_state_boundary_fires_apx701(self):
        """Rebuild the zero_dp8_adam_step with the exact bug the SPMD
        auditor shipped against (bench.py carried the ZeRO state
        through its shard_map boundary as P()): the m/v buffers come
        out shard-sized-but-replicated and APX701 names them."""
        from apex_tpu.contrib.optimizers import (
            distributed_fused_adam, zero_adam_plan)

        plan = zero_adam_plan(8, axis_name="zero")
        mesh = plan.make_mesh()
        params = {"w": jnp.ones((512, 16), jnp.float32)}
        grads = {"w": jnp.full((512, 16), 1e-3, jnp.float32)}
        tx = distributed_fused_adam(1e-2, axis_name="zero",
                                    use_pallas=False)
        # THE BUG: out_specs/in_specs P() — each device's 1/8 state
        # shard presented as a replicated global
        state = shard_map(tx.init, mesh=mesh, in_specs=P(),
                          out_specs=P(), check_vma=False)(params)

        def step(p, s, g):
            def shard(p, s, g):
                import optax

                u, s2 = tx.update(g, s, p)
                return optax.apply_updates(p, u), s2

            return shard_map(shard, mesh=mesh,
                             in_specs=(P(), P(), P()),
                             out_specs=(P(), P()),
                             check_vma=False)(p, s, g)

        ep = eps.EntryPoint(
            name="zero_bugged", plan=lambda: plan,
            build=lambda: (jax.jit(step), (params, state, grads)))
        audit = sharding._audit_one("zero_bugged", ep, REPO)
        fired = {f.rule for f in audit.findings}
        assert "APX701" in fired, "\n".join(
            f.render() for f in audit.findings)
        msgs = [f.message for f in audit.findings
                if f.rule == "APX701"]
        assert any(".m[0]" in m for m in msgs)
        assert any(".v[0]" in m for m in msgs)


# ---------------------------------------------------------------------------
# acceptance: the committed repo state is green
# ---------------------------------------------------------------------------

class TestRepoSharded:
    def test_every_planned_entry_audits_clean_vs_baseline(self):
        unsuppressed, advisories, stale, audits = \
            sharding.run_sharding_check(REPO)
        assert unsuppressed == [], "\n".join(
            f.render() for f in unsuppressed)
        assert stale == []
        assert {"gpt_dp8_train_step", "zero_dp8_update_step",
                "zero_dp8_adam_step", "moe_ep8_train_step"} \
            <= set(audits)
        # ISSUE-19 closed ROADMAP item 3's a2a/compute overlap: the
        # chunked expert exchange leaves the MoE entry advisory-free
        # (the legacy a2a_chunks=1 fixture above still fires it)
        assert not any(f.rule == "APX704" and "moe_ep8" in f.message
                       for f in advisories)

    def test_baseline_commits_the_plans(self):
        base = sharding.load_sharding_baseline(repo_root=REPO)
        row = base["entries"]["zero_dp8_adam_step"]
        axes = row["plan"]["axes"]
        assert axes == [{"kind": "zero", "name": "zero", "size": 8}]
        assert [r"\.(m|v)\b", ["zero"]] in row["plan"]["tensor_specs"]
        assert {"reduce_scatter", "all_gather"} <= \
            set(row["collectives"])

    def test_zero_adam_state_is_really_sharded(self):
        """The positive twin of the bug fixture: the registered entry
        compiles with m/v propagated P('zero') — per-device 1/8."""
        ep = eps.ENTRY_POINTS["zero_dp8_adam_step"]
        fn, args = ep.build()
        compiled = fn.lower(*args).compile()
        in_paths = sharding._arg_paths(args)
        shardings = sharding._flatten_shardings(
            compiled.input_shardings[0])
        by_path = dict(zip(in_paths, shardings))
        m_global = jax.tree_util.tree_leaves(args[1].m)[0]
        m_sh = by_path["in1.m[0]"]
        assert m_sh.shard_shape(m_global.shape)[0] == \
            m_global.shape[0] // 8

    def test_partial_update_preserves_unaudited_rows(self, tmp_path):
        import shutil

        (tmp_path / "tools").mkdir()
        shutil.copy(os.path.join(REPO, "tools",
                                 "sharding_baseline.json"),
                    tmp_path / "tools" / "sharding_baseline.json")
        audits = sharding.audit_sharding(
            REPO, names=["zero_dp8_update_step"])
        assert list(audits) == ["zero_dp8_update_step"]
        sharding.write_sharding_baseline(audits,
                                         repo_root=str(tmp_path))
        after = sharding.load_sharding_baseline(
            repo_root=str(tmp_path))
        before = sharding.load_sharding_baseline(repo_root=REPO)
        assert set(after["entries"]) == set(before["entries"])
        assert after["entries"]["moe_ep8_train_step"] == \
            before["entries"]["moe_ep8_train_step"]

    def test_filtered_run_does_not_stale_other_suppressions(
            self, tmp_path):
        import shutil

        (tmp_path / "tools").mkdir()
        shutil.copy(os.path.join(REPO, "tools",
                                 "sharding_baseline.json"),
                    tmp_path / "tools" / "sharding_baseline.json")
        (tmp_path / "tools" / "sharding_findings.txt").write_text(
            "<entry:moe_ep8_train_step>:APX703:budget.psum.over"
            "  # hypothetical\n")
        # audits restricted to the zero entry: the moe suppression is
        # not judged; but the restricted entry's own keys are
        unsuppressed, _, stale, _ = sharding.run_sharding_check(
            str(tmp_path), names=["zero_dp8_update_step"])
        assert stale == []

    def test_cli_check_sharding_green(self):
        from apex_tpu.analysis.__main__ import main

        assert main(["--check-sharding", "--root", REPO]) == 0

    def test_suppression_entry_parses_entry_prefixed_keys(self):
        # the path itself contains a colon — a naive split(":") read
        # "<entry" and attributed dot-less symbols to no entry
        assert sharding._suppression_entry(
            "<entry:zero_dp8_adam_step>:APX705:per-device-mem") == \
            "zero_dp8_adam_step"
        assert sharding._suppression_entry(
            "apex_tpu/x.py:APX702:moe_ep8_train_step.f.all_gather") \
            == "moe_ep8_train_step"
        assert sharding._suppression_entry(
            "orphan:APX900:nodots") is None


# ---------------------------------------------------------------------------
# satellites: linter --paths fast path; multichip topology column
# ---------------------------------------------------------------------------

class TestPathsFilter:
    def test_filtered_lint_scopes_rules_like_the_full_walk(self,
                                                           tmp_path):
        from apex_tpu.analysis import linter

        pkg = tmp_path / "apex_tpu"
        pkg.mkdir()
        # package file: full rule set (broad except -> APX202)
        (pkg / "mod.py").write_text(
            "try:\n    x = 1\nexcept Exception:\n    pass\n")
        # compat-scope file: APX501 only (the except is NOT reported)
        tests_dir = tmp_path / "tests"
        tests_dir.mkdir()
        (tests_dir / "t.py").write_text(
            "from jax.experimental.shard_map import shard_map\n"
            "try:\n    x = 1\nexcept Exception:\n    pass\n")
        # outside both: not lint surface
        (tmp_path / "scratch.py").write_text("import os\n")
        out = linter.lint_paths(
            repo_root=str(tmp_path),
            paths=["apex_tpu/mod.py", "tests/t.py", "scratch.py",
                   "deleted.py"])
        rules = sorted((f.path, f.rule) for f in out)
        assert rules == [("apex_tpu/mod.py", "APX202"),
                         ("tests/t.py", "APX501")]

    def test_filtered_run_check_skips_staleness(self, tmp_path):
        from apex_tpu.analysis import linter

        (tmp_path / "apex_tpu").mkdir()
        (tmp_path / "apex_tpu" / "ok.py").write_text("x = 1\n")
        (tmp_path / "tools").mkdir()
        (tmp_path / "tools" / "analysis_baseline.txt").write_text(
            "apex_tpu/gone.py:APX202:f  # old\n")
        unsuppressed, stale = linter.run_check(
            repo_root=str(tmp_path), paths=["apex_tpu/ok.py"])
        assert unsuppressed == [] and stale == []
        # the full walk DOES judge it stale
        _, stale_full = linter.run_check(repo_root=str(tmp_path))
        assert stale_full == ["apex_tpu/gone.py:APX202:f"]

    def test_repo_paths_fast_path_matches_full_walk_subset(self):
        from apex_tpu.analysis import linter

        target = "apex_tpu/analysis/sharding.py"
        fast = linter.lint_paths(repo_root=REPO, paths=[target])
        full = [f for f in linter.lint_paths(repo_root=REPO)
                if f.path == target]
        assert sorted(f.key for f in fast) == \
            sorted(f.key for f in full)


class TestTopologyColumn:
    def _readme_numbers(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "readme_numbers",
            os.path.join(REPO, "tools", "readme_numbers.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_plans_match_committed_topology_file(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "graft_entry", os.path.join(REPO, "__graft_entry__.py"))
        graft = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(graft)
        payload = graft._plans_payload(8)
        with open(os.path.join(REPO, "MULTICHIP_TOPOLOGY.json")) as f:
            committed = json.load(f)
        assert payload == committed
        assert committed["legs"]["gpt_3d"]["describe"] == \
            "pipe=2(pipeline) x data=2(data) x tensor=2(tensor)"
        assert committed["legs"]["zero_adam"]["describe"] == \
            "data=8(zero)"

    def test_topology_rows_prefer_multichip_tail(self, tmp_path):
        rn = self._readme_numbers()
        (tmp_path / "MULTICHIP_r07.json").write_text(json.dumps({
            "n_devices": 8, "tail":
                "[dryrun] GPT 3D train step OK: loss=4.2\n"
                "[dryrun] plan gpt_3d: pipe=2(pipeline) x "
                "data=2(data) x tensor=2(tensor)\n"
                "[dryrun] plan zero_adam: data=8(zero)\n"}))
        rows = rn.topology_rows(str(tmp_path))
        assert rows == [
            ("gpt_3d",
             "pipe=2(pipeline) x data=2(data) x tensor=2(tensor)"),
            ("zero_adam", "data=8(zero)")]

    def test_topology_rows_fall_back_to_topology_file(self, tmp_path):
        rn = self._readme_numbers()
        # a pre-plan-line artifact (old tail) + the committed topology
        (tmp_path / "MULTICHIP_r05.json").write_text(json.dumps({
            "n_devices": 8, "tail": "[dryrun] OK on 8 devices\n"}))
        (tmp_path / "MULTICHIP_TOPOLOGY.json").write_text(json.dumps({
            "legs": {"gpt_3d": {"describe": "pipe=2(pipeline)"},
                     "ulysses": {"describe": "sequence=4(sequence)"}}}))
        assert rn.topology_rows(str(tmp_path)) == [
            ("gpt_3d", "pipe=2(pipeline)"),
            ("ulysses", "sequence=4(sequence)")]
        # neither source: no rows, no crash
        empty = tmp_path / "empty"
        empty.mkdir()
        assert rn.topology_rows(str(empty)) == []

    def test_render_includes_topology_rows(self):
        rn = self._readme_numbers()
        block = rn.render({}, "X.json",
                          topo=[("gpt_3d", "pipe=2(pipeline)")])
        assert "| multichip topology — gpt_3d | `pipe=2(pipeline)` |" \
            in block

    def test_moe_perf_rows_from_multichip_tail(self, tmp_path):
        """ISSUE-19: the '[dryrun] perf moe_ep <topology>: ...' lines
        parse into (topology, step_ms, tokens_s) triples and render as
        README rows; artifacts predating the perf lines yield none."""
        rn = self._readme_numbers()
        (tmp_path / "MULTICHIP_r07.json").write_text(json.dumps({
            "n_devices": 8, "tail":
                "[dryrun] expert-parallel MoE OK over expert=4\n"
                "[dryrun] perf moe_ep expert=2: step_ms=3.821 "
                "tokens_s=268015 (fused dispatch, a2a_chunks=2)\n"
                "[dryrun] perf moe_ep expert=4: step_ms=4.787 "
                "tokens_s=213927 (fused dispatch, a2a_chunks=2)\n"}))
        rows = rn.moe_perf_rows(str(tmp_path))
        assert rows == [("expert=2", "3.821", "268015"),
                        ("expert=4", "4.787", "213927")]
        block = rn.render({}, "X.json", moe_perf=rows)
        assert ("| multichip MoE layer — expert=2 (host substrate) | "
                "3.821 ms/step, 268015 tok/s |") in block
        # pre-perf-line artifact: no rows, no crash
        (tmp_path / "MULTICHIP_r07.json").write_text(json.dumps({
            "n_devices": 8, "tail": "[dryrun] OK on 8 devices\n"}))
        assert rn.moe_perf_rows(str(tmp_path)) == []

    def test_render_includes_moe_ep_bench_rows(self):
        """The bench moe_ep section's headline rows render from the
        artifact: fused-vs-onehot speedup and EP decode tokens/s."""
        rn = self._readme_numbers()
        block = rn.render({"extras": {"moe_ep": {
            "shape": {"capacity_factor": 1.25},
            "moe_layer": {"fused_vs_onehot": 4.487,
                          "fused_vs_dense": 1.332},
            "ep_decode": {"tokens_per_sec": 600.67}}}}, "X.json")
        assert "4.487x faster" in block
        assert "600.67 tok/s" in block
        assert "cf 1.25 padding" in block

    def test_dryrun_prints_one_plan_line_per_leg(self):
        """The stdout contract the MULTICHIP_rNN.json tail records:
        sorted '[dryrun] plan <leg>: <axes>' lines derived from the
        canonical constructors (no subprocess — the print loop's
        source of truth is multichip_plans, asserted directly)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "graft_entry", os.path.join(REPO, "__graft_entry__.py"))
        graft = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(graft)
        plans = graft.multichip_plans(8)
        assert set(plans) == {
            "gpt_3d", "interleaved_pp", "sequence_ring", "ulysses",
            "expert_parallel", "tp_x_ep", "zero_adam", "resnet_dp",
            "serving_tp", "serving_ep"}
        for plan in plans.values():
            assert plan.axes  # every leg records real axes
        # kinds cover the full parallelism alphabet
        kinds = {a.kind for p in plans.values() for a in p.axes}
        assert kinds == {"data", "tensor", "pipeline", "sequence",
                         "expert", "zero"}


# ---------------------------------------------------------------------------
# MeshPlan adoption in the parallel stack
# ---------------------------------------------------------------------------

class TestPlanAdoption:
    def test_parallel_state_registers_a_plan(self):
        from apex_tpu import parallel_state

        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2,
            pipeline_model_parallel_size=2)
        plan = parallel_state.get_mesh_plan()
        assert plan.describe() == \
            "pipe=2(pipeline) x data=2(data) x tensor=2(tensor)"
        assert plan.make_mesh().shape == \
            dict(parallel_state.get_mesh().shape)

    def test_layer_plans_price_their_collectives(self):
        from apex_tpu.transformer.expert_parallel import (
            ExpertParallelMLP)
        from apex_tpu.transformer.pipeline_parallel import (
            pipeline_plan)
        from apex_tpu.transformer.sequence_parallel import (
            SequenceParallelTransformerLayer)

        # 2 a2a hops per capacity chunk, x2 for the backward
        # transposes (default APEX_TPU_MOE_A2A_CHUNKS=2 -> 8)
        ep = ExpertParallelMLP(16, 32, num_experts=8).mesh_plan(4)
        assert ep.budget() == {"all_to_all": 8}
        ep1 = ExpertParallelMLP(16, 32, num_experts=8,
                                a2a_chunks=1).mesh_plan(4)
        assert ep1.budget() == {"all_to_all": 4}
        assert ep.spec_for("in0['wi']") == ("expert",)
        assert ep.spec_for("in0['router']") == ()
        ring = SequenceParallelTransformerLayer(
            16, 4, causal=True).mesh_plan(4)
        assert ring.budget() == {"ppermute": 12}  # 2*(P-1)*2
        uly = SequenceParallelTransformerLayer(
            16, 4, causal=True, mode="ulysses").mesh_plan(4)
        assert uly.budget() == {"all_to_all": 8}
        pp = pipeline_plan(4, 8)
        assert pp.budget() == {"ppermute": 22}  # (8+4-1) ticks x2
        vpp = pipeline_plan(4, 4, virtual_pipeline_size=2)
        assert vpp.budget() == {"ppermute": 44}  # 11 ticks x2 hops x2

    def test_plan_axis_name_mismatch_raises(self):
        from apex_tpu.transformer.expert_parallel import (
            ExpertParallelMLP)

        plan = MeshPlan.build(axes=(("ep", 4, "expert"),))
        layer = ExpertParallelMLP(16, 32, num_experts=4, plan=plan)
        assert layer.axis_name == "ep"
        with pytest.raises(ValueError, match="expert axis"):
            ExpertParallelMLP(16, 32, num_experts=4, plan=plan,
                              axis_name="other")

    def test_finding_is_dataclass_renderable(self):
        # the Finding plumbing --json uses
        plan = _plan8()
        audit = sharding.ShardingAudit(
            name="fx", plan_json=plan.to_json(),
            per_device_bytes=None, census={}, findings=[],
            advisories=[])
        row = audit.baseline_row()
        assert dataclasses.asdict(audit)["name"] == "fx"
        assert row["per_device_bytes"] is None
