"""Profiling stack tests.

Models the reference's ``tests/L0/run_pyprof_nvtx`` /
``run_pyprof_data`` suites: annotation payloads, and FLOP/byte analytical
models checked against hand-computed values (ref:
apex/pyprof/prof/{blas,conv}.py formulas).
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import pyprof
from apex_tpu.pyprof import nvtx, prof


class TestNvtx:
    def test_annotate_passthrough_when_disabled(self):
        nvtx.disable()

        @pyprof.annotate
        def f(x):
            return x * 2

        np.testing.assert_array_equal(np.asarray(f(jnp.ones(3))),
                                      [2, 2, 2])

    def test_annotate_enabled_and_jittable(self):
        pyprof.init()
        try:
            @pyprof.annotate(name="my_block")
            def f(x):
                return x * 2 + 1

            out = jax.jit(f)(jnp.ones((4,)))
            np.testing.assert_array_equal(np.asarray(out), [3, 3, 3, 3])
            # the scope name must reach the jaxpr name stack
            jaxpr = jax.make_jaxpr(f)(jnp.ones((4,)))
            assert "my_block" in str(jaxpr.eqns[0].source_info.name_stack)
        finally:
            nvtx.disable()

    def test_call_signature_payload(self):
        sig = nvtx.call_signature("mm", (jnp.ones((2, 3)),), {"k": 4},
                                  module="jnp")
        d = json.loads(sig)
        assert d["op"] == "mm"
        assert d["args"][0]["shape"] == [2, 3]
        assert d["kwargs"]["k"] == 4

    def test_push_pop_and_range(self):
        pyprof.push("region")
        pyprof.pop()
        with pyprof.range_annotation("scoped"):
            pass


class TestProfAnalytical:
    def test_matmul_flops(self):
        # ref blas model: 2*M*N*K (prof/blas.py:340)
        recs = prof.analyze(lambda a, b: a @ b,
                            jnp.ones((128, 256)), jnp.ones((256, 64)))
        dots = [r for r in recs if r.op == "dot_general"]
        assert len(dots) == 1
        assert dots[0].flops == 2 * 128 * 64 * 256

    def test_batched_matmul_flops(self):
        recs = prof.analyze(
            lambda a, b: jnp.einsum("bij,bjk->bik", a, b),
            jnp.ones((4, 8, 16)), jnp.ones((4, 16, 32)))
        dots = [r for r in recs if r.op == "dot_general"]
        assert sum(r.flops for r in dots) == 2 * 4 * 8 * 32 * 16

    def test_conv_flops(self):
        # ref conv model: 2 * out_numel * Cin * kh * kw (prof/conv.py:236)
        x = jnp.ones((2, 16, 16, 8))
        k = jnp.ones((3, 3, 8, 32))
        recs = prof.analyze(
            lambda x, k: jax.lax.conv_general_dilated(
                x, k, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")), x, k)
        convs = [r for r in recs if r.op == "conv_general_dilated"]
        out_numel = 2 * 16 * 16 * 32
        assert convs[0].flops == 2 * out_numel * 8 * 9

    def test_depthwise_conv_flops(self):
        # grouped conv: kernel in-feature dim is already Cin/groups, so
        # flops = 2 * out_numel * 1 * kh * kw for depthwise
        cin = 16
        x = jnp.ones((2, 8, 8, cin))
        k = jnp.ones((3, 3, 1, cin))
        recs = prof.analyze(
            lambda x, k: jax.lax.conv_general_dilated(
                x, k, (1, 1), "SAME", feature_group_count=cin,
                dimension_numbers=("NHWC", "HWIO", "NHWC")), x, k)
        convs = [r for r in recs if r.op == "conv_general_dilated"]
        out_numel = 2 * 8 * 8 * cin
        assert convs[0].flops == 2 * out_numel * 1 * 9

    def test_bytes_accounting(self):
        x = jnp.ones((1024,), jnp.float32)
        recs = prof.analyze(lambda x: x + 1.0, x)
        adds = [r for r in recs if r.op == "add"]
        # operand + broadcast scalar-ish + output; at least in+out
        assert adds[0].bytes >= 2 * 4096

    def test_scan_multiplies_counts(self):
        def f(x):
            def body(c, _):
                return c @ w, None
            w = jnp.ones((8, 8))
            out, _ = jax.lax.scan(body, x, None, length=10)
            return out

        recs = prof.analyze(f, jnp.ones((8, 8)))
        dots = [r for r in recs if r.op == "dot_general"]
        assert dots and dots[0].count == 10
        assert dots[0].flops == 10 * 2 * 8 * 8 * 8

    def test_named_scope_attribution(self):
        def f(x):
            with jax.named_scope("attention"):
                y = x @ x
            return y

        recs = prof.analyze(f, jnp.ones((4, 4)))
        dots = [r for r in recs if r.op == "dot_general"]
        assert any("attention" in r.scope for r in dots)

    def test_report_tsv(self):
        recs = prof.analyze(lambda a, b: jax.nn.relu(a @ b),
                            jnp.ones((32, 32)), jnp.ones((32, 32)))
        tsv = prof.report(recs)
        lines = tsv.splitlines()
        assert lines[0].startswith("idx\top")
        assert lines[-1].startswith("TOTAL")
        assert any("dot_general" in l for l in lines)

    def test_summary_by_op(self):
        recs = prof.analyze(lambda a, b: jax.nn.relu(a @ b),
                            jnp.ones((32, 32)), jnp.ones((32, 32)))
        s = prof.summary_by_op(recs)
        assert "dot_general" in s
        assert next(iter(s)) == "dot_general"  # sorted by flops

    def test_xla_cost_analysis_crosscheck(self):
        got = prof.xla_cost_analysis(lambda a, b: a @ b,
                                     jnp.ones((64, 64)),
                                     jnp.ones((64, 64)))
        if "flops" in got:  # CPU backend may not report
            assert got["flops"] == pytest.approx(2 * 64 ** 3, rel=0.5)

    def test_measure_runs(self):
        dt = prof.measure(lambda x: x * 2, jnp.ones((128,)), iters=3)
        assert dt >= 0.0

    def test_train_step_analysis_end_to_end(self):
        # the VERDICT bar: profiling a train step yields an op-level table
        import optax

        from apex_tpu import amp

        params = {"w1": jnp.ones((32, 64)), "w2": jnp.ones((64, 8))}
        cast, opt, state = amp.initialize(params, optax.sgd(0.1),
                                          opt_level="O5")
        x = jnp.ones((16, 32), jnp.bfloat16)

        def train_step(p, st):
            def loss_fn(p):
                h = jax.nn.relu(x @ p["w1"])
                return opt.scale_loss(jnp.sum(h @ p["w2"]), st)
            g = jax.grad(loss_fn)(p)
            new_p, new_st, _ = opt.apply_gradients(g, st, p)
            return new_p, new_st

        recs = prof.analyze(train_step, cast, state)
        assert prof.total_flops(recs) > 2 * 2 * 16 * 32 * 64  # fwd+bwd
        tsv = prof.report(recs, top=20)
        assert "dot_general" in tsv


class TestProfileSession:
    def test_trace_writes_logdir(self, tmp_path):
        logdir = str(tmp_path / "tb")
        with pyprof.trace(logdir):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        assert os.path.isdir(logdir)
        # jax.profiler writes plugins/profile/<run>/
        found = []
        for root, _dirs, files in os.walk(logdir):
            found += files
        assert found, "trace produced no files"

    def test_profile_window(self, tmp_path):
        w = pyprof.ProfileWindow(str(tmp_path / "tb2"), 2, 4)
        for it in range(6):
            w.step(it)
            jax.block_until_ready(jnp.ones((4,)) * it)
        assert w._ctx is None  # closed by step(4), not leaked
        w.close()
        assert os.path.isdir(str(tmp_path / "tb2"))

    def test_profile_window_empty_never_opens(self, tmp_path):
        w = pyprof.ProfileWindow(str(tmp_path / "tb3"), 3, 3)
        for it in range(6):
            w.step(it)
        assert w._ctx is None

    def test_profile_window_closes_on_iteration_jump(self, tmp_path):
        w = pyprof.ProfileWindow(str(tmp_path / "tb4"), 1, 3)
        w.step(1)
        assert w._ctx is not None
        w.step(10)  # checkpoint-resume style jump past stop_iter
        assert w._ctx is None

    def test_trace_timer_conflict_does_not_leak_profiler(self, tmp_path):
        from apex_tpu.transformer.pipeline_parallel.utils import Timers

        timers = Timers()
        timers("w").start()  # already running
        with pytest.raises(RuntimeError):
            with pyprof.trace(str(tmp_path / "tb5"), timers=timers,
                              name="w"):
                pass
        timers("w").stop()
        # profiler must still be usable
        with pyprof.trace(str(tmp_path / "tb6")):
            jax.block_until_ready(jnp.ones((4,)))
