"""Native prefetch loader: C++/Python parity, determinism, epoch
semantics (the reference's DataLoader contract,
ref: examples/imagenet/main_amp.py:228-236, re-tested the apex_tpu way:
everything host-only and bitwise-checkable)."""
import numpy as np
import pytest

from apex_tpu.data import DataLoader, device_prefetch, native_available
from apex_tpu.data.loader import _epoch_perm

N, HW, C = 64, 4, 3
BATCH = 8


def _dataset(dtype=np.float32):
    rng = np.random.RandomState(0)
    if dtype == np.uint8:
        images = rng.randint(0, 256, (N, HW, HW, C)).astype(np.uint8)
    else:
        images = rng.randn(N, HW, HW, C).astype(np.float32)
    labels = rng.randint(0, 10, (N,)).astype(np.int32)
    return images, labels


class TestPythonBackend:
    def test_epoch_covers_dataset_once(self):
        images, labels = _dataset()
        dl = DataLoader(images, labels, BATCH, seed=3, backend="python")
        seen = []
        for _ in range(len(dl)):
            x, y = next(dl)
            assert x.shape == (BATCH, HW, HW, C) and x.dtype == np.float32
            seen.append(x[:, 0, 0, 0])
        flat = np.concatenate(seen)
        # every example served exactly once per epoch
        np.testing.assert_allclose(np.sort(flat),
                                   np.sort(images[:, 0, 0, 0]))

    def test_deterministic_and_epoch_dependent(self):
        images, labels = _dataset()
        a = DataLoader(images, labels, BATCH, seed=7, backend="python")
        b = DataLoader(images, labels, BATCH, seed=7, backend="python")
        xa, ya = next(a)
        xb, yb = next(b)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
        # second epoch reshuffles
        e0 = _epoch_perm(N, 7, 0)
        e1 = _epoch_perm(N, 7, 1)
        assert not np.array_equal(e0, e1)
        # seed 0 = sequential
        np.testing.assert_array_equal(_epoch_perm(N, 0, 5), np.arange(N))

    def test_uint8_normalization(self):
        images, labels = _dataset(np.uint8)
        mean, std = (0.5, 0.4, 0.3), (0.2, 0.3, 0.4)
        dl = DataLoader(images, labels, BATCH, seed=0, mean=mean, std=std,
                        backend="python")
        x, y = next(dl)
        ref = (images[:BATCH].astype(np.float32) / 255.0
               - np.array(mean, np.float32)) / np.array(std, np.float32)
        np.testing.assert_allclose(x, ref, rtol=1e-6)
        np.testing.assert_array_equal(y, labels[:BATCH])

    def test_validation_errors(self):
        images, labels = _dataset()
        with pytest.raises(ValueError, match="dtype"):
            DataLoader(images.astype(np.float64), labels, BATCH)
        with pytest.raises(ValueError, match="batch_size"):
            DataLoader(images, labels, N + 1)
        with pytest.raises(ValueError, match="mean"):
            DataLoader(images, labels, BATCH, mean=(0.5,))


@pytest.mark.skipif(not native_available(),
                    reason="no C++ toolchain for the native loader")
class TestNativeBackend:
    def test_matches_python_bitwise_float32(self):
        images, labels = _dataset()
        nat = DataLoader(images, labels, BATCH, seed=11, num_threads=3,
                         backend="native")
        py = DataLoader(images, labels, BATCH, seed=11, backend="python")
        try:
            for _ in range(3 * len(nat)):  # spans 3 epochs
                xn, yn = next(nat)
                xp, yp = next(py)
                np.testing.assert_array_equal(xn, xp)
                np.testing.assert_array_equal(yn, yp)
        finally:
            nat.close()

    def test_matches_python_bitwise_uint8_norm(self):
        images, labels = _dataset(np.uint8)
        kw = dict(mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225))
        nat = DataLoader(images, labels, BATCH, seed=5, num_threads=2,
                         backend="native", **kw)
        py = DataLoader(images, labels, BATCH, seed=5, backend="python",
                        **kw)
        try:
            for _ in range(len(nat)):
                xn, yn = next(nat)
                xp, yp = next(py)
                np.testing.assert_allclose(xn, xp, rtol=1e-6, atol=1e-7)
                np.testing.assert_array_equal(yn, yp)
        finally:
            nat.close()

    def test_start_batch_resume_alignment(self):
        """start_batch=k must continue exactly where a fresh loader
        would be after serving k batches (O(1) resume contract)."""
        images, labels = _dataset()
        k = 5
        fresh = DataLoader(images, labels, BATCH, seed=13,
                           backend="native")
        resumed = DataLoader(images, labels, BATCH, seed=13,
                             backend="native", start_batch=k)
        try:
            for _ in range(k):
                next(fresh)
            for _ in range(len(fresh)):
                xf, yf = next(fresh)
                xr, yr = next(resumed)
                np.testing.assert_array_equal(xf, xr)
                np.testing.assert_array_equal(yf, yr)
        finally:
            fresh.close()
            resumed.close()

    def test_prefetch_order_stable_across_thread_counts(self):
        images, labels = _dataset()
        a = DataLoader(images, labels, BATCH, seed=2, num_threads=1,
                       backend="native")
        b = DataLoader(images, labels, BATCH, seed=2, num_threads=4,
                       prefetch_depth=4, backend="native")
        try:
            for _ in range(2 * len(a)):
                xa, _ = next(a)
                xb, _ = next(b)
                np.testing.assert_array_equal(xa, xb)
        finally:
            a.close()
            b.close()


def test_device_prefetch_preserves_order():
    images, labels = _dataset()
    dl = DataLoader(images, labels, BATCH, seed=9, backend="python")
    direct = [next(DataLoader(images, labels, BATCH, seed=9,
                              backend="python"))[1]
              for _ in range(1)][0]
    got = []
    for i, (x, y) in enumerate(device_prefetch(_take(dl, 4), size=2)):
        got.append(np.asarray(y))
        if i == 0:
            np.testing.assert_array_equal(np.asarray(y), direct)
    assert len(got) == 4


def _take(it, k):
    for _ in range(k):
        yield next(it)


class TestCursorCheckpointResume:
    """Loader-cursor resume through the checkpoint layer: the cursor
    rides ``CheckpointManager`` ``extra`` and the resumed run sees the
    exact remaining batch sequence — no replay, no skip (the O(1)
    ``start_batch`` contract, end to end through Orbax)."""

    def test_cursor_roundtrip_exact_remaining_sequence(self, tmp_path):
        import jax.numpy as jnp

        from apex_tpu.utils import CheckpointManager

        images, labels = _dataset()
        seed, total, consumed = 11, 2 * (N // BATCH), 5  # spans epochs

        reference = DataLoader(images, labels, BATCH, seed=seed,
                               backend="python")
        ref_batches = [next(reference) for _ in range(total)]

        # consume 5 batches, checkpoint the cursor mid-epoch-stream
        run1 = DataLoader(images, labels, BATCH, seed=seed,
                          backend="python")
        for k in range(consumed):
            xa, ya = next(run1)
            np.testing.assert_array_equal(xa, ref_batches[k][0])
        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            mgr.save(consumed, {"w": jnp.zeros(())},
                     extra={"loader_cursor": jnp.int32(run1._cursor)})
            mgr.wait()

        # a fresh process restores the cursor and resumes the stream
        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            _, _, extra, step = mgr.restore(
                {"w": jnp.zeros(())},
                extra={"loader_cursor": jnp.int32(0)})
        assert step == consumed
        cursor = int(extra["loader_cursor"])
        assert cursor == consumed
        run2 = DataLoader(images, labels, BATCH, seed=seed,
                          backend="python", start_batch=cursor)
        for k in range(consumed, total):
            xr, yr = next(run2)
            xf, yf = ref_batches[k]  # no replay of k<consumed, no skip
            np.testing.assert_array_equal(xr, xf)
            np.testing.assert_array_equal(yr, yf)
