"""int8 weight-only matmul: kernel-vs-twin parity (APX401/402 surface)
and the quantizer's degenerate-row discipline (ISSUE-16)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.quant_matmul import (QuantGPTServingWeights,
                                       SCALE_FLOOR, dequantize_weight,
                                       is_quantized_weights,
                                       quant_matmul,
                                       quant_matmul_reference,
                                       quantize_weight,
                                       quantize_weights, self_check)


def _qw(key, k, n, scale=1.0):
    w = jax.random.normal(key, (k, n), jnp.float32) * scale
    return (w,) + quantize_weight(w)


# --- kernel vs twin -------------------------------------------------------

@pytest.mark.parametrize("batch", [1, 4, 8])
def test_gemv_parity(batch):
    """The decode fast path (M <= 8) against the jnp twin."""
    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    _, wq, sc = _qw(kw, 128, 384)
    x = jax.random.normal(kx, (batch, 128), jnp.float32)
    got = quant_matmul(x, wq, sc, backend="pallas")
    want = quant_matmul_reference(x, wq, sc)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("k,n", [(96, 160), (100, 130), (192, 72)])
def test_odd_dims_parity(k, n):
    """Odd K/N zero-pad to kernel tiles; padded columns slice off."""
    kx, kw = jax.random.split(jax.random.PRNGKey(1))
    _, wq, sc = _qw(kw, k, n)
    x = jax.random.normal(kx, (4, k), jnp.float32)
    got = quant_matmul(x, wq, sc, backend="pallas")
    want = quant_matmul_reference(x, wq, sc)
    assert got.shape == (4, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_tiled_parity():
    """The prefill path (M > 8, M-tiled grid)."""
    kx, kw = jax.random.split(jax.random.PRNGKey(2))
    _, wq, sc = _qw(kw, 128, 256)
    x = jax.random.normal(kx, (200, 128), jnp.float32)
    got = quant_matmul(x, wq, sc, backend="pallas")
    want = quant_matmul_reference(x, wq, sc)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_saturating_amax_inputs():
    """Columns driven to exactly +/-amax hit the +/-127 codes — no
    wraparound, kernel and twin agree bit-for-bit."""
    k, n = 64, 128
    w = np.zeros((k, n), np.float32)
    w[0, :] = np.linspace(-3.0, 3.0, n)     # the amax row per column
    w[1, :] = -w[0, :]
    wq, sc = quantize_weight(jnp.asarray(w))
    assert int(jnp.max(wq)) == 127 and int(jnp.min(wq)) == -127
    x = jnp.ones((8, k), jnp.float32) * 5.0
    got = quant_matmul(x, wq, sc, backend="pallas")
    want = quant_matmul_reference(x, wq, sc)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_leading_dims_and_out_dtype():
    kx, kw = jax.random.split(jax.random.PRNGKey(3))
    _, wq, sc = _qw(kw, 128, 128)
    x = jax.random.normal(kx, (2, 3, 128), jnp.bfloat16)
    got = quant_matmul(x, wq, sc, backend="pallas")
    assert got.shape == (2, 3, 128) and got.dtype == jnp.bfloat16
    want = quant_matmul_reference(x, wq, sc)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_backend_dispatch_and_validation():
    _, wq, sc = _qw(jax.random.PRNGKey(4), 64, 64)
    x = jnp.ones((2, 64))
    # default backend off-TPU is the twin (XLA fallback)
    np.testing.assert_allclose(
        np.asarray(quant_matmul(x, wq, sc)),
        np.asarray(quant_matmul_reference(x, wq, sc)))
    with pytest.raises(ValueError, match="backend"):
        quant_matmul(x, wq, sc, backend="cuda")
    with pytest.raises(ValueError, match="int8"):
        quant_matmul(x, wq.astype(jnp.int32), sc)
    with pytest.raises(ValueError, match="mismatch"):
        quant_matmul(jnp.ones((2, 63)), wq, sc)
    with pytest.raises(ValueError, match="mismatch"):
        quant_matmul(x, wq, sc[:-1])


def test_self_check_runs():
    self_check()


# --- quantizer ------------------------------------------------------------

def test_quantize_round_trip_error_bound():
    w, wq, sc = _qw(jax.random.PRNGKey(5), 128, 96, scale=2.0)
    deq = dequantize_weight(wq, sc)
    # symmetric int8: worst-case error is half a quantization step
    step = np.asarray(sc)[None, :]
    assert np.all(np.abs(np.asarray(deq - w)) <= step * 0.5 + 1e-7)


def test_all_zero_channel_round_trips_exactly():
    """The degenerate-row regression (ISSUE-16 satellite): an all-zero
    output channel must round-trip to exactly 0.0 — scale floored at
    SCALE_FLOOR, never a 0/0 NaN on either side."""
    w = np.zeros((64, 8), np.float32)
    w[:, 3] = 1.0                       # one live channel
    wq, sc = quantize_weight(jnp.asarray(w))
    assert np.all(np.isfinite(np.asarray(sc)))
    assert float(jnp.min(sc)) == pytest.approx(SCALE_FLOOR / 127.0)
    deq = np.asarray(dequantize_weight(wq, sc))
    assert np.all(deq[:, :3] == 0.0) and np.all(deq[:, 4:] == 0.0)
    np.testing.assert_allclose(deq[:, 3], w[:, 3])
    for backend in ("pallas", "xla"):
        out = quant_matmul(jnp.ones((2, 64)), wq, sc, backend=backend)
        assert np.all(np.isfinite(np.asarray(out)))
        assert np.all(np.asarray(out)[:, :3] == 0.0)


def test_quantize_weight_validates_rank():
    with pytest.raises(ValueError, match="expects"):
        quantize_weight(jnp.ones((4, 4, 4)))


# --- the GPT pytree conversion -------------------------------------------

def test_quantize_weights_pytree():
    from apex_tpu.serving.model import (GPTServingWeights, LayerWeights)

    h, f, v, s = 32, 128, 64, 16
    key = jax.random.PRNGKey(6)
    ks = jax.random.split(key, 8)
    lw = LayerWeights(
        ln1_w=jnp.ones((h,)), ln1_b=jnp.zeros((h,)),
        qkv_k=jax.random.normal(ks[0], (h, 3 * h)),
        qkv_b=jnp.zeros((3 * h,)),
        dense_k=jax.random.normal(ks[1], (h, h)),
        dense_b=jnp.zeros((h,)),
        ln2_w=jnp.ones((h,)), ln2_b=jnp.zeros((h,)),
        fc1_k=jax.random.normal(ks[2], (h, f)),
        fc1_b=jnp.zeros((f,)),
        fc2_k=jax.random.normal(ks[3], (f, h)),
        fc2_b=jnp.zeros((h,)))
    w = GPTServingWeights(
        wte=jax.random.normal(ks[4], (v, h)),
        wpe=jax.random.normal(ks[5], (s, h)),
        layers=(lw, lw), lnf_w=jnp.ones((h,)), lnf_b=jnp.zeros((h,)))
    qw = quantize_weights(w)
    assert isinstance(qw, QuantGPTServingWeights)
    assert not is_quantized_weights(w) and is_quantized_weights(qw)
    assert len(qw.layers) == 2
    ql = qw.layers[0]
    assert ql.qkv_k.dtype == jnp.int8 and ql.qkv_s.shape == (3 * h,)
    assert ql.fc2_k.dtype == jnp.int8 and ql.fc2_s.shape == (h,)
    # embeddings / norms / biases ride through untouched
    assert qw.wte is w.wte and ql.ln1_w is lw.ln1_w
    assert ql.qkv_b is lw.qkv_b
    # dequantized kernels approximate the originals
    np.testing.assert_allclose(
        np.asarray(dequantize_weight(ql.dense_k, ql.dense_s)),
        np.asarray(lw.dense_k), atol=float(jnp.max(ql.dense_s)) * 0.51)
