"""End-to-end example-driver tests.

Models the reference's L1 tier: the full imagenet driver run as a user
would run it, on a deterministic tiny real-data (.npz) set — the
convergence evidence VERDICT weak #9 asked for — plus checkpoint
resume through the driver.
"""
import importlib.util
import os
import sys

import numpy as np
import pytest

# a site-packages 'examples' package shadows the repo's; load by path
_spec = importlib.util.spec_from_file_location(
    "apex_tpu_example_main_amp",
    os.path.join(os.path.dirname(__file__), "..", "examples", "imagenet",
                 "main_amp.py"))
main_amp = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(main_amp)


def _make_npz(path, n=256, size=32, classes=4, seed=0,
              dtype=np.float32):
    """Separable dataset: class-dependent color means + noise.
    ``dtype=np.uint8`` stores [0, 1]-clipped values scaled to bytes
    (the realistic image storage format)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, size=n).astype(np.int32)
    if dtype == np.uint8:
        means = rng.uniform(0.2, 0.8, size=(classes, 3)).astype(
            np.float32)
        images = np.clip(means[labels][:, None, None, :]
                         + 0.1 * rng.randn(n, size, size, 3), 0, 1)
        images = (images * 255).astype(np.uint8)
    else:
        means = rng.uniform(-1, 1, size=(classes, 3)).astype(np.float32)
        images = (means[labels][:, None, None, :]
                  + 0.3 * rng.randn(n, size, size, 3)).astype(np.float32)
    np.savez(path, images=images, labels=labels)
    return path


class TestImagenetDriverNpz:
    @pytest.mark.slow
    def test_npz_convergence_tiny_resnet(self, tmp_path):
        """Real-data loss curve: the driver must learn a separable
        4-class set well below chance level (-ln(1/4) = 1.386)."""
        npz = _make_npz(str(tmp_path / "tiny.npz"))
        final_loss = main_amp.main([
            "--data", npz, "--arch", "resnet_tiny",
        "--devices", "1",
            "--batch-size", "32", "--iters", "60", "--epochs", "1",
            "--image-size", "32", "--num-classes", "4",
            "--lr", "0.02", "--opt-level", "O5", "--deterministic",
            "--print-freq", "50",
            "--checkpoint", str(tmp_path / "ck.msgpack"),
        ])
        assert final_loss < 0.9, f"no convergence on npz data: {final_loss}"


    @pytest.mark.slow
    def test_native_loader_convergence_and_determinism(self, tmp_path):
        """The DataLoader path (C++ prefetch workers when available)
        must also learn, and be run-to-run deterministic despite
        multithreaded prefetch."""
        npz = _make_npz(str(tmp_path / "tinyL.npz"))
        argv = [
            "--data", npz, "--arch", "resnet_tiny",
            "--devices", "1", "--loader", "auto", "--loader-threads", "3",
            "--batch-size", "32", "--iters", "60", "--epochs", "1",
            "--image-size", "32", "--num-classes", "4",
            "--lr", "0.02", "--opt-level", "O5", "--deterministic",
            "--print-freq", "50",
            "--checkpoint", str(tmp_path / "ckL.msgpack"),
        ]
        first = main_amp.main(argv)
        second = main_amp.main(argv)
        assert first < 0.9, f"no convergence via DataLoader: {first}"
        assert first == second, (first, second)

    def test_uint8_dataset_through_native_loader(self, tmp_path):
        """uint8 storage (the realistic image format): the loader's
        worker-side v/255 normalization must feed the driver and
        converge — exercises the C++ uint8 path end to end."""
        from apex_tpu.data import native_available

        if not native_available():
            pytest.skip("no C++ toolchain for the native loader")
        npz = _make_npz(str(tmp_path / "tiny_u8.npz"), seed=3,
                        dtype=np.uint8)
        final_loss = main_amp.main([
            "--data", npz, "--arch", "resnet_tiny",
            "--devices", "1", "--loader", "native",
            "--batch-size", "32", "--iters", "60", "--epochs", "1",
            "--image-size", "32", "--num-classes", "4",
            "--lr", "0.02", "--opt-level", "O5", "--deterministic",
            "--print-freq", "50",
            "--checkpoint", str(tmp_path / "cku8.msgpack"),
        ])
        assert final_loss < 0.9, f"no convergence on uint8 data: " \
                                 f"{final_loss}"


    @pytest.mark.slow
    def test_npz_deterministic_across_runs(self, tmp_path):
        """Same seed + deterministic flag => bitwise-equal loss curves
        (the L1 compare.py exact-equality oracle,
        ref: tests/L1/common/compare.py:36-50)."""
        npz = _make_npz(str(tmp_path / "tiny2.npz"))
        logs = []
        for run in range(2):
            log = str(tmp_path / f"loss_{run}.log")
            main_amp.main([
                "--data", npz, "--arch", "resnet_tiny",
        "--devices", "1",
                "--batch-size", "16", "--iters", "8", "--epochs", "1",
                "--image-size", "32", "--num-classes", "4",
                "--opt-level", "O5", "--deterministic",
                "--print-freq", "50", "--loss-log", log,
                "--checkpoint", str(tmp_path / f"ck{run}.msgpack"),
            ])
            with open(log) as f:
                logs.append(f.read())
        assert logs[0] == logs[1], "nondeterministic loss curve"


    @pytest.mark.slow
    def test_resume_continues_from_checkpoint(self, tmp_path):
        npz = _make_npz(str(tmp_path / "tiny3.npz"))
        ck = str(tmp_path / "resume.msgpack")
        main_amp.main([
            "--data", npz, "--arch", "resnet_tiny",
        "--devices", "1",
            "--batch-size", "16", "--iters", "4", "--epochs", "1",
            "--image-size", "32", "--num-classes", "4",
            "--opt-level", "O5", "--print-freq", "50",
            "--checkpoint", ck,
        ])
        assert os.path.exists(ck)
        # resumed run starts at step 4
        log = str(tmp_path / "resume.log")
        main_amp.main([
            "--data", npz, "--arch", "resnet_tiny",
        "--devices", "1",
            "--batch-size", "16", "--iters", "2", "--epochs", "1",
            "--image-size", "32", "--num-classes", "4",
            "--opt-level", "O5", "--print-freq", "50",
            "--resume", ck, "--checkpoint", ck, "--loss-log", log,
        ])
        with open(log) as f:
            first_step = int(f.read().split()[0])
        assert first_step == 5  # steps 5,6 logged after resuming at 4
